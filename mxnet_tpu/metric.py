"""Evaluation metrics.

Reference: python/mxnet/metric.py — the EvalMetric zoo (Accuracy,
TopKAccuracy, F1, MCC, Perplexity, MAE/MSE/RMSE, CrossEntropy, NLL, Pearson,
Loss, CustomMetric, CompositeEvalMetric) plus the string registry used by
``Module.fit(eval_metric="acc")``. Metric math runs on host numpy: metric
updates are per-batch reductions of already-materialized predictions and
feeding them back through XLA would force extra device syncs.
"""
from __future__ import annotations

import math
from collections import OrderedDict

import numpy

from .base import MXNetError

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss",
           "CustomMetric", "np", "create", "register"]

_METRIC_REGISTRY = {}


def register(klass, *names):
    key_names = names or (klass.__name__,)
    for name in key_names:
        _METRIC_REGISTRY[name.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    """Create by name / callable / list (reference: metric.py create)."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, CompositeEvalMetric):
        return metric
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite_metric = CompositeEvalMetric()
        for child_metric in metric:
            composite_metric.add(create(child_metric, *args, **kwargs))
        return composite_metric
    if isinstance(metric, str):
        try:
            return _METRIC_REGISTRY[metric.lower()](*args, **kwargs)
        except KeyError:
            raise ValueError(f"Metric must be either callable or in registry; "
                             f"got {metric}")
    raise TypeError(f"metric should be str/callable/EvalMetric, got "
                    f"{type(metric)}")


def _as_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else numpy.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """Reference: metric.py:36 check_label_shapes."""
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(f"Shape of labels {label_shape} does not match "
                         f"shape of predictions {pred_shape}")
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    return labels, preds


class EvalMetric:
    """Base metric (reference: metric.py:59)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def _accum(self, value, n=1):
        """Add ``value`` over ``n`` instances to both the epoch-local and
        the global (reset_local-surviving) tallies."""
        self.sum_metric += value
        self.global_sum_metric += value
        self.num_inst += n
        self.global_num_inst += n

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        name, value = self.get_global()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (reference: metric.py:298)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(f"Metric index {index} is out of range 0 and "
                              f"{len(self.metrics)}")

    @staticmethod
    def _restrict(d, names):
        if names is None:
            return d
        return OrderedDict((k, v) for k, v in d.items() if k in names)

    def update_dict(self, labels, preds):
        labels = self._restrict(labels, self.label_names)
        preds = self._restrict(preds, self.output_names)
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def reset_local(self):
        try:
            for metric in self.metrics:
                metric.reset_local()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, numpy.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_global(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get_global()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, numpy.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_config(self):
        config = super().get_config()
        config.update({"metrics": [i.get_config() for i in self.metrics]})
        return config


@register
class Accuracy(EvalMetric):
    """Classification accuracy (reference: metric.py:386)."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, axis=axis, output_names=output_names,
                         label_names=label_names)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            pred_np = _as_numpy(pred_label)
            label_np = _as_numpy(label)
            if pred_np.shape != label_np.shape:
                pred_np = numpy.argmax(pred_np, axis=self.axis)
            pred_np = pred_np.astype("int32").flatten()
            label_np = label_np.astype("int32").flatten()
            check_label_shapes(label_np, pred_np)
            num_correct = (pred_np == label_np).sum()
            self.sum_metric += num_correct
            self.global_sum_metric += num_correct
            self.num_inst += len(pred_np)
            self.global_num_inst += len(pred_np)


_METRIC_REGISTRY["acc"] = Accuracy


@register
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (reference: metric.py:462)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names,
                         label_names=label_names)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, \
                "Predictions should be no more than 2 dims"
            pred_np = _as_numpy(pred_label).astype("float32")
            num_dims = len(pred_np.shape)
            if num_dims == 2:
                pred_np = numpy.argsort(pred_np, axis=1)
            label_np = _as_numpy(label).astype("int32")
            num_samples = pred_np.shape[0]
            if num_dims == 1:
                num_correct = (pred_np.flatten() == label_np.flatten()).sum()
                self.sum_metric += num_correct
                self.global_sum_metric += num_correct
            elif num_dims == 2:
                num_classes = pred_np.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    num_correct = (pred_np[:, num_classes - 1 - j].flatten()
                                   == label_np.flatten()).sum()
                    self.sum_metric += num_correct
                    self.global_sum_metric += num_correct
            self.num_inst += num_samples
            self.global_num_inst += num_samples


_METRIC_REGISTRY["top_k_accuracy"] = TopKAccuracy
_METRIC_REGISTRY["top_k_acc"] = TopKAccuracy


class _BinaryClassificationMetrics:
    """Confusion bookkeeping shared by F1/MCC.

    Where the reference (metric.py:576) maintains eight scalar counters,
    the epoch-local and global tallies here are two 2x2 arrays indexed
    ``[label, prediction]`` — one vectorised bincount per batch updates
    the whole table, and every derived statistic reads off it."""

    def __init__(self):
        self._local = numpy.zeros((2, 2), numpy.int64)
        self._global = numpy.zeros((2, 2), numpy.int64)

    def update_binary_stats(self, label, pred):
        pred_np = _as_numpy(pred)
        label_np = _as_numpy(label).astype("int32")
        check_label_shapes(label_np, pred_np)
        if len(numpy.unique(label_np)) > 2:
            raise ValueError("%s currently only supports binary "
                             "classification." % self.__class__.__name__)
        # collapse to {0,1}: class-1 is "positive", everything else
        # (including argmax hits on extra columns) is "negative"
        is_pos = (numpy.argmax(pred_np, axis=1).ravel() == 1)
        truth = (label_np.ravel() == 1)
        delta = numpy.bincount(2 * truth + is_pos,
                               minlength=4).reshape(2, 2)
        self._local += delta
        self._global += delta

    @staticmethod
    def _prf(conf):
        """(precision, recall, fscore) of a 2x2 [label, pred] table."""
        tp = conf[1, 1]
        prec = tp / conf[:, 1].sum() if conf[:, 1].any() else 0.0
        rec = tp / conf[1, :].sum() if conf[1, :].any() else 0.0
        f = 2 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0
        return float(prec), float(rec), float(f)

    @property
    def precision(self):
        return self._prf(self._local)[0]

    @property
    def recall(self):
        return self._prf(self._local)[1]

    @property
    def fscore(self):
        return self._prf(self._local)[2]

    @property
    def global_fscore(self):
        return self._prf(self._global)[2]

    def matthewscc(self, use_global=False):
        conf = self._global if use_global else self._local
        if not conf.any():
            return 0.0
        ((tn, fp), (fn, tp)) = conf.astype(numpy.float64)
        # product of the four marginals, with empty marginals dropped
        # (the reference's convention, metric.py:876) rather than the
        # textbook 0-denominator
        marginals = numpy.asarray([tp + fp, tp + fn, tn + fp, tn + fn])
        denom = marginals[marginals != 0].prod()
        return (tp * tn - fp * fn) / math.sqrt(denom)

    @property
    def total_examples(self):
        return int(self._local.sum())

    @property
    def global_total_examples(self):
        return int(self._global.sum())

    def reset_stats(self):
        self._local[:] = 0

    def reset(self):
        self._local[:] = 0
        self._global[:] = 0


@register
class F1(EvalMetric):
    """Binary F1 (reference: metric.py:714)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(label, pred)
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.global_sum_metric += self.metrics.fscore
            self.num_inst += 1
            self.global_num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * self.metrics.total_examples
            self.global_sum_metric = (self.metrics.global_fscore
                                      * self.metrics.global_total_examples)
            self.num_inst = self.metrics.total_examples
            self.global_num_inst = self.metrics.global_total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0
        self.metrics.reset()

    def reset_local(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        self.metrics.reset_stats()


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference: metric.py:811)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self._average = average
        self._metrics = _BinaryClassificationMetrics()
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        stats = self._metrics
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            stats.update_binary_stats(label, pred)
        if self._average == "macro":
            # one coefficient sample per update() call: the local table
            # restarts, the global one keeps accumulating
            self.sum_metric += stats.matthewscc()
            self.num_inst += 1
            self.global_sum_metric += stats.matthewscc(use_global=True)
            self.global_num_inst += 1
            stats.reset_stats()
        else:
            # micro: one coefficient over every example seen, expressed
            # as sum/count so get() recovers it unchanged
            self.sum_metric = stats.matthewscc() * stats.total_examples
            self.num_inst = stats.total_examples
            self.global_sum_metric = (stats.matthewscc(use_global=True)
                                      * stats.global_total_examples)
            self.global_num_inst = stats.global_total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self.global_sum_metric = 0.0
        self.global_num_inst = 0.0
        self._metrics.reset()

    def reset_local(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self._metrics.reset_stats()


@register
class Perplexity(EvalMetric):
    """Perplexity (reference: metric.py:938)."""

    def __init__(self, ignore_label, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label,
                         output_names=output_names, label_names=label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label_np = _as_numpy(label).astype("int32")
            pred_np = _as_numpy(pred)
            assert label_np.size == pred_np.size / pred_np.shape[-1], \
                f"shape mismatch: {label_np.shape} vs. {pred_np.shape}"
            label_flat = label_np.reshape((label_np.size,))
            probs = pred_np.reshape(-1, pred_np.shape[-1])[
                numpy.arange(label_flat.size), label_flat]
            if self.ignore_label is not None:
                ignore = (label_flat == self.ignore_label).astype(probs.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label_flat.size
        self.sum_metric += loss
        self.global_sum_metric += loss
        self.num_inst += num
        self.global_num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name,
                math.exp(self.global_sum_metric / self.global_num_inst))


@register
class MAE(EvalMetric):
    """Mean absolute error (reference: metric.py:1025)."""

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_numpy(label)
            pred_np = _as_numpy(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            mae = numpy.abs(label_np - pred_np).mean()
            self.sum_metric += mae
            self.global_sum_metric += mae
            self.num_inst += 1
            self.global_num_inst += 1


@register
class MSE(EvalMetric):
    """Mean squared error (reference: metric.py:1083)."""

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_numpy(label)
            pred_np = _as_numpy(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            mse = ((label_np - pred_np) ** 2.0).mean()
            self.sum_metric += mse
            self.global_sum_metric += mse
            self.num_inst += 1
            self.global_num_inst += 1


@register
class RMSE(EvalMetric):
    """Root mean squared error (reference: metric.py:1141)."""

    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_numpy(label)
            pred_np = _as_numpy(pred)
            if len(label_np.shape) == 1:
                label_np = label_np.reshape(label_np.shape[0], 1)
            if len(pred_np.shape) == 1:
                pred_np = pred_np.reshape(pred_np.shape[0], 1)
            rmse = numpy.sqrt(((label_np - pred_np) ** 2.0).mean())
            self.sum_metric += rmse
            self.global_sum_metric += rmse
            self.num_inst += 1
            self.global_num_inst += 1


@register
class CrossEntropy(EvalMetric):
    """Cross entropy over class probabilities (reference:
    metric.py:1199)."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_numpy(label)
            pred_np = _as_numpy(pred)
            label_flat = label_np.ravel()
            assert label_flat.shape[0] == pred_np.shape[0]
            prob = pred_np[numpy.arange(label_flat.shape[0]),
                           numpy.int64(label_flat)]
            cross_entropy = (-numpy.log(prob + self.eps)).sum()
            self.sum_metric += cross_entropy
            self.global_sum_metric += cross_entropy
            self.num_inst += label_flat.shape[0]
            self.global_num_inst += label_flat.shape[0]


_METRIC_REGISTRY["ce"] = CrossEntropy


@register
class NegativeLogLikelihood(EvalMetric):
    """NLL (reference: metric.py:1265)."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, eps=eps, output_names=output_names,
                         label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label_np = _as_numpy(label)
            pred_np = _as_numpy(pred)
            label_flat = label_np.ravel()
            num_examples = pred_np.shape[0]
            assert label_flat.shape[0] == num_examples, \
                (label_flat.shape, pred_np.shape)
            prob = pred_np[numpy.arange(num_examples),
                           numpy.int64(label_flat)]
            nll = (-numpy.log(prob + self.eps)).sum()
            self.sum_metric += nll
            self.global_sum_metric += nll
            self.num_inst += num_examples
            self.global_num_inst += num_examples


_METRIC_REGISTRY["nll_loss"] = NegativeLogLikelihood


@register
class PearsonCorrelation(EvalMetric):
    """Pearson correlation (reference: metric.py:1330).

    ``average='micro'`` computes one coefficient over every example
    seen. Where the reference merges per-batch means/variances with a
    Welford-style update, here the five raw moments (sums of x, y, x^2,
    y^2, xy) are accumulated in float64 and the coefficient is formed
    once at ``get()`` — the streaming state is a single vector."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0
        # n, sum_l, sum_p, sum_ll, sum_pp, sum_lp
        self._moments = numpy.zeros(6, numpy.float64)
        self._anchor = None

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, False, True)
            lab = _as_numpy(label).ravel().astype(numpy.float64)
            prd = _as_numpy(pred).ravel().astype(numpy.float64)
            if self.average == "macro":
                self._accum(numpy.corrcoef(prd, lab)[0, 1])
            else:
                self._accum(0.0)  # the value lives in the moments
                if self._anchor is None:
                    # Pearson is shift-invariant; centering every batch
                    # on the first batch's means keeps the accumulated
                    # squares O(variance) instead of O(mean^2), so
                    # large-mean data (timestamps, raw prices) does not
                    # cancel away the float64 mantissa
                    self._anchor = (lab.mean(), prd.mean())
                lab = lab - self._anchor[0]
                prd = prd - self._anchor[1]
                self._moments += (lab.size, lab.sum(), prd.sum(),
                                  lab @ lab, prd @ prd, lab @ prd)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        if self.average == "macro":
            return (self.name, self.sum_metric / self.num_inst)
        n, sl, sp, sll, spp, slp = self._moments
        cov = n * slp - sl * sp
        denom = numpy.sqrt((n * sll - sl * sl) * (n * spp - sp * sp))
        return (self.name, cov / denom if denom != 0 else float("nan"))


_METRIC_REGISTRY["pcc"] = PearsonCorrelation


@register
class Loss(EvalMetric):
    """Dummy metric averaging a pre-computed loss output (reference:
    metric.py:1477)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, _, preds):
        if isinstance(preds, list) and len(preds) > 0 \
                and not hasattr(preds[0], "asnumpy") \
                and not isinstance(preds[0], numpy.ndarray):
            preds = [preds]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            loss = _as_numpy(pred).sum()
            self.sum_metric += loss
            self.global_sum_metric += loss
            n = 1
            for s in numpy.shape(_as_numpy(pred)):
                n *= s
            self.num_inst += n
            self.global_num_inst += n


@register
class CustomMetric(EvalMetric):
    """Wrap a ``feval(label, pred)`` function (reference: metric.py:1549)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = f"custom({name})"
        super().__init__(name, feval=feval,
                         allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            # feval returns either a bare value (counted as one
            # instance) or a (sum, count) pair
            result = self._feval(_as_numpy(label), _as_numpy(pred))
            self._accum(*(result if isinstance(result, tuple)
                          else (result,)))

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Create CustomMetric from a numpy feval (reference:
    metric.py:1625)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


@register
class VOCMApMetric(EvalMetric):
    """Pascal-VOC mean average precision for detection.

    Reference: example/ssd/evaluate/eval_metric.py (MApMetric /
    VOC07MApMetric). ``update(labels, preds)`` takes ground truth
    (N, G, >=5) rows [cls, x1, y1, x2, y2, (difficult)] padded with -1,
    and detections (N, A, 6) rows [cls, score, x1, y1, x2, y2] with
    suppressed rows cls=-1 (the MultiBoxDetection output convention).
    AP per class from the precision/recall curve; ``use_07_metric``
    selects the VOC-2007 11-point interpolation.
    """

    def __init__(self, iou_thresh=0.5, class_names=None,
                 use_07_metric=False, name="mAP", **kwargs):
        self.iou_thresh = iou_thresh
        self.class_names = class_names
        self.use_07_metric = use_07_metric
        super().__init__(name, **kwargs)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0
        # per-class accumulators: scores, tp flags, gt counts
        self._records = {}
        self._gt_counts = {}

    def update(self, labels, preds):
        import numpy as onp

        for label, pred in zip(labels, preds):
            lab = label.asnumpy() if hasattr(label, "asnumpy") else \
                onp.asarray(label)
            det = pred.asnumpy() if hasattr(pred, "asnumpy") else \
                onp.asarray(pred)
            for b in range(lab.shape[0]):
                self._update_one(lab[b], det[b])

    @staticmethod
    def _iou_matrix(a, b):
        """(D, 4) x (G, 4) corner-box IoU via numpy broadcast."""
        import numpy as onp

        iw = (onp.minimum(a[:, None, 2], b[None, :, 2]) -
              onp.maximum(a[:, None, 0], b[None, :, 0])).clip(min=0)
        ih = (onp.minimum(a[:, None, 3], b[None, :, 3]) -
              onp.maximum(a[:, None, 1], b[None, :, 1])).clip(min=0)
        inter = iw * ih
        area_a = ((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]))[:, None]
        area_b = ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))[None, :]
        return inter / onp.maximum(area_a + area_b - inter, 1e-12)

    def _update_one(self, gts, dets):
        import numpy as onp

        gts = gts[gts[:, 0] >= 0]
        dets = dets[dets[:, 0] >= 0]
        # VOC protocol: 'difficult' ground truths (column 5 when present)
        # count neither toward recall nor as false positives
        difficult = (gts[:, 5] > 0 if gts.shape[1] > 5
                     else onp.zeros(len(gts), bool))
        order = onp.argsort(-dets[:, 1])
        dets = dets[order]
        for c in onp.unique(onp.concatenate([gts[:, 0], dets[:, 0]])):
            sel = gts[:, 0] == c
            gt_c = gts[sel][:, 1:5]
            diff_c = difficult[sel]
            det_c = dets[dets[:, 0] == c]
            self._gt_counts[c] = self._gt_counts.get(c, 0) + \
                int((~diff_c).sum())
            rec = self._records.setdefault(c, [])
            taken = onp.zeros(len(gt_c), bool)
            iou = (self._iou_matrix(det_c[:, 2:6], gt_c)
                   if len(gt_c) and len(det_c) else
                   onp.zeros((len(det_c), 0)))
            for di, d in enumerate(det_c):
                bi = int(onp.argmax(iou[di])) if iou.shape[1] else -1
                best = iou[di, bi] if bi >= 0 else 0.0
                if best >= self.iou_thresh and bi >= 0:
                    if diff_c[bi]:
                        continue        # matched a difficult gt: ignore
                    tp = not taken[bi]
                    taken[bi] = True
                else:
                    tp = False
                rec.append((float(d[1]), bool(tp)))

    def _average_precision(self, rec_list, n_gt):
        import numpy as onp

        if n_gt == 0:
            return None
        if not rec_list:
            return 0.0
        rec_list = sorted(rec_list, key=lambda t: -t[0])
        tp = onp.cumsum([t[1] for t in rec_list])
        fp = onp.cumsum([not t[1] for t in rec_list])
        recall = tp / n_gt
        precision = tp / onp.maximum(tp + fp, 1e-12)
        if self.use_07_metric:
            ap = 0.0
            for t in onp.arange(0.0, 1.1, 0.1):
                p = precision[recall >= t].max() if (recall >= t).any() \
                    else 0.0
                ap += p / 11.0
            return float(ap)
        # exact area under the interpolated PR curve
        mrec = onp.concatenate([[0.0], recall, [1.0]])
        mpre = onp.concatenate([[0.0], precision, [0.0]])
        for i in range(len(mpre) - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = onp.where(mrec[1:] != mrec[:-1])[0]
        return float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())

    def get(self):
        aps = []
        for c, n_gt in self._gt_counts.items():
            ap = self._average_precision(self._records.get(c, []), n_gt)
            if ap is not None:
                aps.append(ap)
        value = float(sum(aps) / len(aps)) if aps else float("nan")
        return self.name, value


@register
class VOC07MApMetric(VOCMApMetric):
    """11-point interpolated VOC-2007 mAP (reference:
    example/ssd/evaluate/eval_metric.py VOC07MApMetric)."""

    def __init__(self, iou_thresh=0.5, class_names=None, name="mAP07",
                 **kwargs):
        super().__init__(iou_thresh=iou_thresh, class_names=class_names,
                         use_07_metric=True, name=name, **kwargs)
