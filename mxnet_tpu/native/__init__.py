"""Native (C++) runtime components, loaded via ctypes.

The reference implements its data pipeline in C++ (dmlc-core recordio +
src/io/ prefetching iterators); this package holds the TPU-native
equivalents. Each component compiles on first use with the host
toolchain (g++) into ``_build/`` and is cached by source mtime; every
caller keeps a pure-Python fallback, so a missing toolchain degrades
gracefully (set MXNET_TPU_NATIVE=0 to force the fallback).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

__all__ = ["recordio_lib", "imagepipe_lib", "native_enabled",
           "predict_lib_path", "predict_header_path"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "recordio_native.cpp")
_BUILD = os.path.join(_DIR, "_build")
_SO = os.path.join(_BUILD, "librecordio_native.so")
_IP_SRC = os.path.join(_DIR, "src", "imagepipe_native.cpp")
_IP_SO = os.path.join(_BUILD, "libimagepipe_native.so")
_PRED_SRC = os.path.join(_DIR, "src", "predict_c.cpp")
_PRED_SO = os.path.join(_BUILD, "libmxtpu_predict.so")
_PRED_HDR = os.path.join(_DIR, "include", "mxtpu_predict.h")

_lock = threading.Lock()
_lib = "unset"
_ip_lib = "unset"


def native_enabled() -> bool:
    return os.environ.get("MXNET_TPU_NATIVE", "1") != "0"


def _compile(src, so, extra=()):
    os.makedirs(_BUILD, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src, "-o", so + ".tmp", *extra]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(so + ".tmp", so)


def _build():
    _compile(_SRC, _SO)


def recordio_lib():
    """The compiled recordio library, or None (no toolchain / disabled).
    Thread-safe; compiles at most once per process."""
    global _lib
    if not native_enabled():   # honored per call, not only at first load
        return None
    if _lib != "unset":
        return _lib
    with _lock:
        if _lib != "unset":
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
        except Exception:
            _lib = None
            return None
        lib.rio_open_reader.restype = ctypes.c_void_p
        lib.rio_open_reader.argtypes = [ctypes.c_char_p]
        lib.rio_read.restype = ctypes.c_long
        lib.rio_read.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.POINTER(
                                     ctypes.c_ubyte))]
        lib.rio_read_at.restype = ctypes.c_long
        lib.rio_read_at.argtypes = [ctypes.c_void_p, ctypes.c_long,
                                    ctypes.POINTER(ctypes.POINTER(
                                        ctypes.c_ubyte))]
        lib.rio_seek.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.rio_tell.restype = ctypes.c_long
        lib.rio_tell.argtypes = [ctypes.c_void_p]
        lib.rio_error.restype = ctypes.c_char_p
        lib.rio_error.argtypes = [ctypes.c_void_p]
        lib.rio_close.argtypes = [ctypes.c_void_p]
        lib.rio_open_prefetch.restype = ctypes.c_void_p
        lib.rio_open_prefetch.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.rio_pf_read.restype = ctypes.c_long
        lib.rio_pf_read.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.POINTER(
                                        ctypes.c_ubyte))]
        lib.rio_pf_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def imagepipe_lib():
    """The compiled decode/augment pipeline (needs the system OpenCV
    C++ libs — the same dependency the reference's C++ ImageRecordIter
    has), or None. Thread-safe; compiles at most once per process."""
    global _ip_lib
    if not native_enabled():
        return None
    if _ip_lib != "unset":
        return _ip_lib
    with _lock:
        if _ip_lib != "unset":
            return _ip_lib
        try:
            if (not os.path.exists(_IP_SO)
                    or os.path.getmtime(_IP_SO)
                    < os.path.getmtime(_IP_SRC)):
                _compile(_IP_SRC, _IP_SO,
                         extra=("-I/usr/include/opencv4", "-lopencv_core",
                                "-lopencv_imgcodecs", "-lopencv_imgproc"))
            lib = ctypes.CDLL(_IP_SO)
        except Exception:
            _ip_lib = None
            return None
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.ip_create.restype = ctypes.c_void_p
        lib.ip_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, f32p, f32p, ctypes.c_int]
        lib.ip_start_epoch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_uint32]
        lib.ip_next_batch.restype = ctypes.c_long
        lib.ip_next_batch.argtypes = [ctypes.c_void_p, f32p, f32p]
        lib.ip_error_count.restype = ctypes.c_long
        lib.ip_error_count.argtypes = [ctypes.c_void_p]
        lib.ip_last_error.restype = ctypes.c_char_p
        lib.ip_last_error.argtypes = [ctypes.c_void_p]
        lib.ip_destroy.argtypes = [ctypes.c_void_p]
        _ip_lib = lib
        return lib


def _python_build_flags():
    """(include_flags, link_flags) for embedding this interpreter
    (what `python3-config --includes --ldflags --embed` reports)."""
    import sysconfig
    inc = ["-I" + sysconfig.get_path("include")]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_python_version()
    link = []
    if libdir:
        link += ["-L" + libdir, "-Wl,-rpath," + libdir]
    link += ["-lpython" + ver, "-ldl", "-lm"]
    return inc, link


def predict_header_path():
    """Path of mxtpu_predict.h for C/C++ hosts to #include."""
    return _PRED_HDR


def predict_lib_path():
    """Compile (once) and return the path of libmxtpu_predict.so — the
    embed-from-C predict shim (reference: c_predict_api). Raises on a
    missing toolchain rather than silently degrading: a C host has no
    Python fallback to fall back to."""
    if not native_enabled():
        raise RuntimeError(
            "native components are disabled (MXNET_TPU_NATIVE=0); the C "
            "predict shim cannot be built")
    with _lock:
        if (not os.path.exists(_PRED_SO)
                or os.path.getmtime(_PRED_SO) < os.path.getmtime(_PRED_SRC)):
            inc, link = _python_build_flags()
            _compile(_PRED_SRC, _PRED_SO, extra=(*inc, *link))
    return _PRED_SO


class NativeRecordReader:
    """Sequential/indexed reader over the C++ core."""

    def __init__(self, path):
        lib = recordio_lib()
        if lib is None:
            raise RuntimeError("native recordio unavailable")
        self._lib = lib
        self._h = lib.rio_open_reader(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path!r}")

    def read(self):
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        n = self._lib.rio_read(self._h, ctypes.byref(buf))
        if n == -1:
            return None
        if n < 0:
            raise IOError(self._lib.rio_error(self._h).decode())
        return ctypes.string_at(buf, n)

    def read_at(self, pos):
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        n = self._lib.rio_read_at(self._h, pos, ctypes.byref(buf))
        if n == -1:
            return None
        if n < 0:
            raise IOError(self._lib.rio_error(self._h).decode())
        return ctypes.string_at(buf, n)

    def seek(self, pos):
        self._lib.rio_seek(self._h, pos)

    def tell(self):
        return self._lib.rio_tell(self._h)

    def close(self):
        if self._h:
            self._lib.rio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePrefetchReader:
    """Background-thread prefetching reader (the C++ thread reads ahead
    ``queue_size`` records while Python consumes)."""

    def __init__(self, path, queue_size=64):
        lib = recordio_lib()
        if lib is None:
            raise RuntimeError("native recordio unavailable")
        self._lib = lib
        self._h = lib.rio_open_prefetch(path.encode(), int(queue_size))
        if not self._h:
            raise IOError(f"cannot open {path!r}")

    def read(self):
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        n = self._lib.rio_pf_read(self._h, ctypes.byref(buf))
        if n == -1:
            return None
        if n < 0:
            raise IOError("prefetch reader failed")
        return ctypes.string_at(buf, n)

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def close(self):
        if self._h:
            self._lib.rio_pf_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
