// Native image-record pipeline: multithreaded JPEG decode + augment.
//
// TPU-native analogue of the reference's C++ ImageRecordIter internals
// (reference: src/io/iter_image_recordio_2.cc:887 — worker threads doing
// cv::imdecode + augmentation into pre-allocated batch buffers). Design
// differences from the reference, on purpose:
//   - the .rec file is mmap'd once; workers read records at offsets the
//     Python side hands them per epoch (shuffle/sharding/padding policy
//     stays in Python where it is testable and mirrors the pure-Python
//     ImageIter exactly),
//   - per-sample RNG is seeded from (epoch_seed, sample_index), so the
//     produced batches are bit-identical regardless of thread count or
//     scheduling — a property the reference does not have,
//   - batches complete in order through a fixed ring of buffers; the
//     consumer copy-out is the only serialized step.
//
// C ABI (driven by mxnet_tpu/image/native_iter.py via ctypes):
//   ip_create / ip_start_epoch / ip_next_batch / ip_error_count /
//   ip_last_error / ip_destroy

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <opencv2/core.hpp>
#include <opencv2/imgcodecs.hpp>
#include <opencv2/imgproc.hpp>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr int kNumBuffers = 3;

#pragma pack(push, 1)
struct IRHeader {        // recordio.py IRHeader, struct fmt "IfQQ"
  uint32_t flag;         // >0: `flag` label floats follow the header
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)

struct Task {
  int64_t batch;     // epoch-global batch index
  int slot;          // position within the batch
  int64_t offset;    // record offset in the .rec file
  int64_t sample_index;  // epoch-global, for deterministic RNG
};

struct Pipe {
  // immutable config
  int batch, h, w, c;
  bool nhwc, rand_crop, rand_mirror;
  int resize_short;          // 0 = off
  int label_width;
  std::vector<float> mean, stdv;  // empty = no normalization

  // mmap'd record file
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t file_size = 0;

  // epoch state (guarded by mu unless noted)
  std::mutex mu;
  std::condition_variable cv_worker, cv_consumer;
  std::deque<Task> tasks;
  uint32_t epoch_seed = 0;
  int64_t nbatches = 0;
  int64_t batches_consumed = 0;   // consumer progress, gates the ring
  int64_t consume_idx = 0;
  std::vector<int> batch_count;   // samples in each batch
  std::vector<std::atomic<int>> remaining;  // per-buffer slots left
  std::vector<int64_t> ready_batch;         // per-buffer: ready batch id
  std::vector<std::vector<float>> buf_data;
  std::vector<std::vector<float>> buf_label;
  int active = 0;                 // workers currently inside a task
  bool shutdown = false;
  std::atomic<long> decode_errors{0};
  std::string last_error;
  std::string last_error_snapshot;  // stable buffer for ip_last_error

  std::vector<std::thread> workers;

  size_t SampleFloats() const { return size_t(h) * w * c; }

  bool Open(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) { last_error = "cannot open rec file"; return false; }
    struct stat st;
    if (fstat(fd, &st) != 0) { last_error = "fstat failed"; return false; }
    file_size = st.st_size;
    base = static_cast<const uint8_t*>(
        mmap(nullptr, file_size, PROT_READ, MAP_PRIVATE, fd, 0));
    if (base == MAP_FAILED) {
      base = nullptr;
      last_error = "mmap failed";
      return false;
    }
    return true;
  }

  ~Pipe() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
      tasks.clear();
    }
    cv_worker.notify_all();
    cv_consumer.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    if (base) munmap(const_cast<uint8_t*>(base), file_size);
    if (fd >= 0) ::close(fd);
  }

  // ---- record parsing ----------------------------------------------------
  // Returns payload span for the record at `off`, or false. Image records
  // are single-chunk (multipart starts at 512MB payloads).
  bool RecordAt(int64_t off, const uint8_t** payload, size_t* len) {
    if (off < 0 || size_t(off) + 8 > file_size) return false;
    uint32_t magic, lrec;
    std::memcpy(&magic, base + off, 4);
    std::memcpy(&lrec, base + off + 4, 4);
    if (magic != kMagic || (lrec >> 29) != 0) return false;
    size_t n = lrec & ((1u << 29) - 1);
    if (size_t(off) + 8 + n > file_size) return false;
    *payload = base + off + 8;
    *len = n;
    return true;
  }

  // ---- per-sample work ---------------------------------------------------
  void DecodeInto(const Task& t) {
    float* out = buf_data[t.batch % kNumBuffers].data() +
                 size_t(t.slot) * SampleFloats();
    float* lab = buf_label[t.batch % kNumBuffers].data() +
                 size_t(t.slot) * label_width;
    const uint8_t* payload;
    size_t len;
    const char* why = nullptr;
    bool ok = RecordAt(t.offset, &payload, &len);
    if (!ok) why = "bad record framing (magic/length/bounds)";
    IRHeader hdr{};
    size_t img_off = sizeof(IRHeader);
    if (ok && len >= sizeof(IRHeader)) {
      std::memcpy(&hdr, payload, sizeof(IRHeader));
      if (hdr.flag > 0) img_off += size_t(hdr.flag) * 4;
      if (img_off > len) { ok = false; why = "header flag overruns record"; }
    } else if (ok) {
      ok = false;
      why = "record shorter than IRHeader";
    }
    // labels: scalar from header, or hdr.flag floats after it
    for (int i = 0; i < label_width; ++i) lab[i] = 0.f;
    if (ok) {
      if (hdr.flag > 0) {
        int n = std::min<int>(label_width, hdr.flag);
        std::memcpy(lab, payload + sizeof(IRHeader), size_t(n) * 4);
      } else {
        lab[0] = hdr.label;
      }
    }

    cv::Mat img;
    if (ok) {
      cv::Mat raw(1, int(len - img_off), CV_8UC1,
                  const_cast<uint8_t*>(payload + img_off));
      img = cv::imdecode(raw, c == 1 ? cv::IMREAD_GRAYSCALE
                                     : cv::IMREAD_COLOR);
      ok = !img.empty();
      if (!ok) why = "image decode failed (corrupt or unsupported codec)";
    }
    if (!ok) {
      decode_errors.fetch_add(1, std::memory_order_relaxed);
      {
        std::lock_guard<std::mutex> lk(mu);
        last_error = "sample " + std::to_string(t.sample_index) +
                     " (rec offset " + std::to_string(t.offset) + "): " +
                     (why ? why : "unknown");
      }
      std::memset(out, 0, SampleFloats() * sizeof(float));
      return;
    }
    if (c == 3) cv::cvtColor(img, img, cv::COLOR_BGR2RGB);

    // deterministic per-sample RNG: independent of thread scheduling
    std::mt19937 rng(epoch_seed * 2654435761u +
                     uint32_t(t.sample_index) * 40503u + 1u);

    if (resize_short > 0) {
      int sh = img.rows, sw = img.cols;
      double scale = double(resize_short) / std::min(sh, sw);
      cv::resize(img, img,
                 cv::Size(std::max(1, int(sw * scale + 0.5)),
                          std::max(1, int(sh * scale + 0.5))),
                 0, 0, cv::INTER_LINEAR);
    }
    if (img.rows < h || img.cols < w) {
      cv::resize(img, img, cv::Size(w, h), 0, 0, cv::INTER_LINEAR);
    }
    int y0, x0;
    if (rand_crop) {
      y0 = img.rows == h ? 0 : int(rng() % uint32_t(img.rows - h + 1));
      x0 = img.cols == w ? 0 : int(rng() % uint32_t(img.cols - w + 1));
    } else {
      y0 = (img.rows - h) / 2;
      x0 = (img.cols - w) / 2;
    }
    cv::Mat crop = img(cv::Rect(x0, y0, w, h));
    bool mirror = rand_mirror && (rng() & 1u);

    const bool norm = !mean.empty();
    for (int y = 0; y < h; ++y) {
      const uint8_t* row = crop.ptr<uint8_t>(y);
      for (int x = 0; x < w; ++x) {
        int xs = mirror ? (w - 1 - x) : x;
        for (int ch = 0; ch < c; ++ch) {
          float v = float(row[xs * c + ch]);
          if (norm) v = (v - mean[ch]) / stdv[ch];
          size_t dst = nhwc
              ? (size_t(y) * w + x) * c + ch
              : size_t(ch) * h * w + size_t(y) * w + x;
          out[dst] = v;
        }
      }
    }
  }

  // ---- worker loop -------------------------------------------------------
  void WorkerLoop() {
    for (;;) {
      Task t;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_worker.wait(lk, [this] {
          return shutdown ||
                 (!tasks.empty() &&
                  tasks.front().batch - batches_consumed < kNumBuffers);
        });
        if (shutdown) return;
        t = tasks.front();
        tasks.pop_front();
        ++active;
      }
      DecodeInto(t);
      {
        std::unique_lock<std::mutex> lk(mu);
        --active;
        auto& rem = remaining[t.batch % kNumBuffers];
        if (rem.fetch_sub(1) == 1) {
          ready_batch[t.batch % kNumBuffers] = t.batch;
          cv_consumer.notify_all();
        }
        if (active == 0 && tasks.empty()) cv_consumer.notify_all();
      }
    }
  }

  // ---- epoch control -----------------------------------------------------
  void StartEpoch(const int64_t* offsets, int64_t n, uint32_t seed) {
    std::unique_lock<std::mutex> lk(mu);
    // abort any in-flight epoch: drop queued work, wait out active tasks
    tasks.clear();
    cv_consumer.wait(lk, [this] { return active == 0; });
    epoch_seed = seed;
    nbatches = (n + batch - 1) / batch;
    batches_consumed = 0;
    consume_idx = 0;
    batch_count.assign(nbatches, batch);
    if (n % batch) batch_count[nbatches - 1] = int(n % batch);
    for (int b = 0; b < kNumBuffers && b < nbatches; ++b)
      remaining[b].store(batch_count[b]);
    for (int b = 0; b < kNumBuffers; ++b) ready_batch[b] = -1;
    for (int64_t i = 0; i < n; ++i)
      tasks.push_back(Task{i / batch, int(i % batch), offsets[i], i});
    cv_worker.notify_all();
  }

  // returns sample count, 0 at epoch end, -1 on error
  long NextBatch(float* out_data, float* out_label) {
    std::unique_lock<std::mutex> lk(mu);
    if (consume_idx >= nbatches) return 0;
    int64_t b = consume_idx;
    cv_consumer.wait(lk, [this, b] {
      return shutdown || ready_batch[b % kNumBuffers] == b;
    });
    if (shutdown) return -1;
    int count = batch_count[b];
    // The buffer is exclusively ours once ready: drop the lock for the
    // ~100MB copy-out so finishing workers don't stall behind it.
    lk.unlock();
    std::memcpy(out_data, buf_data[b % kNumBuffers].data(),
                size_t(count) * SampleFloats() * sizeof(float));
    std::memcpy(out_label, buf_label[b % kNumBuffers].data(),
                size_t(count) * label_width * sizeof(float));
    lk.lock();
    // recycle the buffer for batch b + kNumBuffers
    ready_batch[b % kNumBuffers] = -1;
    if (b + kNumBuffers < nbatches)
      remaining[b % kNumBuffers].store(batch_count[b + kNumBuffers]);
    ++consume_idx;
    ++batches_consumed;
    cv_worker.notify_all();
    return count;
  }
};

}  // namespace

extern "C" {

void* ip_create(const char* rec_path, int batch, int h, int w, int c,
                int nthreads, int nhwc, int resize_short, int rand_crop,
                int rand_mirror, const float* mean, const float* stdv,
                int label_width) {
  Pipe* p = new Pipe();
  p->batch = batch;
  p->h = h;
  p->w = w;
  p->c = c;
  p->nhwc = nhwc != 0;
  p->resize_short = resize_short;
  p->rand_crop = rand_crop != 0;
  p->rand_mirror = rand_mirror != 0;
  p->label_width = label_width > 0 ? label_width : 1;
  if (mean && stdv) {
    p->mean.assign(mean, mean + c);
    p->stdv.assign(stdv, stdv + c);
  }
  if (!p->Open(rec_path)) {
    delete p;
    return nullptr;
  }
  p->buf_data.resize(kNumBuffers);
  p->buf_label.resize(kNumBuffers);
  for (int i = 0; i < kNumBuffers; ++i) {
    p->buf_data[i].resize(size_t(batch) * p->SampleFloats());
    p->buf_label[i].resize(size_t(batch) * p->label_width);
  }
  p->remaining = std::vector<std::atomic<int>>(kNumBuffers);
  p->ready_batch.assign(kNumBuffers, -1);
  if (nthreads < 1) nthreads = 1;
  for (int i = 0; i < nthreads; ++i)
    p->workers.emplace_back([p] { p->WorkerLoop(); });
  return p;
}

void ip_start_epoch(void* h, const int64_t* offsets, int64_t n,
                    uint32_t seed) {
  static_cast<Pipe*>(h)->StartEpoch(offsets, n, seed);
}

long ip_next_batch(void* h, float* out_data, float* out_label) {
  return static_cast<Pipe*>(h)->NextBatch(out_data, out_label);
}

long ip_error_count(void* h) {
  return static_cast<Pipe*>(h)->decode_errors.load();
}

const char* ip_last_error(void* h) {
  // workers update last_error under mu; snapshot it under the same lock
  // so the returned pointer stays stable for the (single-threaded)
  // ctypes caller even while decode threads keep failing
  Pipe* p = static_cast<Pipe*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  p->last_error_snapshot = p->last_error;
  return p->last_error_snapshot.c_str();
}

void ip_destroy(void* h) { delete static_cast<Pipe*>(h); }

}  // extern "C"
