// Native RecordIO reader with threaded prefetch.
//
// TPU-native analogue of the reference's C++ data-pipeline core
// (reference: dmlc-core include/dmlc/recordio.h RecordIOReader/Writer,
// src/io/iter_image_recordio_2.cc's prefetching reader threads). The
// Python framework calls this through ctypes (mxnet_tpu/native/__init__.py);
// mxnet_tpu/recordio.py keeps a pure-Python fallback so the wheel works
// without a toolchain.
//
// Wire format (dmlc-core, byte-compatible with the Python implementation):
//   [u32 magic=0xced7230a][u32 lrec](payload)(pad to 4)
//   lrec = cflag<<29 | length; cflag: 0 whole, 1 begin, 2 middle, 3 end.
//   Multipart records rejoin with the magic word re-inserted at splits.

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Reader {
  FILE* f = nullptr;
  std::vector<uint8_t> record;   // last assembled record
  std::string error;

  explicit Reader(const char* path) { f = std::fopen(path, "rb"); }
  ~Reader() {
    if (f) std::fclose(f);
  }

  // returns: 1 record ready, 0 EOF, -1 error
  int ReadChunk(uint32_t* cflag, std::vector<uint8_t>* out) {
    uint32_t header[2];
    size_t n = std::fread(header, 1, sizeof(header), f);
    if (n == 0) return 0;
    if (n != sizeof(header)) {
      error = "truncated record header";
      return -1;
    }
    if (header[0] != kMagic) {
      error = "invalid record magic";
      return -1;
    }
    *cflag = header[1] >> 29;
    uint32_t len = header[1] & ((1u << 29) - 1);
    out->resize(len);
    if (len && std::fread(out->data(), 1, len, f) != len) {
      error = "truncated record payload";
      return -1;
    }
    uint32_t pad = (4 - (len % 4)) % 4;
    if (pad) {
      uint8_t padbuf[4];
      if (std::fread(padbuf, 1, pad, f) != pad) {
        error = "truncated record padding";
        return -1;
      }
    }
    return 1;
  }

  // assemble one logical record (handles multipart). Same return codes.
  int Next() {
    record.clear();
    uint32_t cflag = 0;
    std::vector<uint8_t> chunk;
    int rc = ReadChunk(&cflag, &chunk);
    if (rc <= 0) return rc;
    if (cflag == 0) {
      record = std::move(chunk);
      return 1;
    }
    if (cflag != 1) {
      error = "unexpected continuation flag";
      return -1;
    }
    const uint8_t magic_bytes[4] = {0x0a, 0x23, 0xd7, 0xce};  // LE
    record = std::move(chunk);
    while (true) {
      rc = ReadChunk(&cflag, &chunk);
      if (rc <= 0) {
        error = "truncated multipart record";
        return -1;
      }
      record.insert(record.end(), magic_bytes, magic_bytes + 4);
      record.insert(record.end(), chunk.begin(), chunk.end());
      if (cflag == 3) return 1;
      if (cflag != 2) {
        error = "unexpected continuation flag";
        return -1;
      }
    }
  }
};

// Bounded-queue prefetcher: one producer thread reads ahead, consumers
// pop assembled records (the reference's iter_image_recordio_2.cc
// producer/consumer split).
struct Prefetcher {
  Reader reader;
  std::deque<std::vector<uint8_t>> queue;
  std::vector<uint8_t> current;     // last popped, owns consumer pointer
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  size_t capacity;
  bool done = false;
  bool failed = false;
  std::thread worker;

  Prefetcher(const char* path, int cap)
      : reader(path), capacity(cap > 0 ? cap : 64) {
    if (reader.f) worker = std::thread([this] { Run(); });
  }

  ~Prefetcher() {
    {
      std::lock_guard<std::mutex> lk(mu);
      done = true;
      capacity = 1u << 30;          // release a blocked producer
    }
    not_full.notify_all();
    not_empty.notify_all();
    if (worker.joinable()) worker.join();
  }

  void Run() {
    while (true) {
      int rc = reader.Next();
      std::unique_lock<std::mutex> lk(mu);
      if (rc <= 0) {
        failed = (rc < 0);
        done = true;
        not_empty.notify_all();
        return;
      }
      not_full.wait(lk, [this] {
        return queue.size() < capacity || done;
      });
      if (done) return;
      queue.push_back(std::move(reader.record));
      not_empty.notify_one();
    }
  }

  // 1 record, 0 EOF, -1 error
  int Pop() {
    std::unique_lock<std::mutex> lk(mu);
    not_empty.wait(lk, [this] { return !queue.empty() || done; });
    if (queue.empty()) return failed ? -1 : 0;
    current = std::move(queue.front());
    queue.pop_front();
    not_full.notify_one();
    return 1;
  }
};

}  // namespace

extern "C" {

void* rio_open_reader(const char* path) {
  Reader* r = new Reader(path);
  if (!r->f) {
    delete r;
    return nullptr;
  }
  return r;
}

// >=0: record length (data -> internal buffer, valid until next call)
// -1: EOF, -2: error
long rio_read(void* h, const uint8_t** data) {
  Reader* r = static_cast<Reader*>(h);
  int rc = r->Next();
  if (rc == 0) return -1;
  if (rc < 0) return -2;
  *data = r->record.data();
  return static_cast<long>(r->record.size());
}

// indexed access: seek then read one record (MXIndexedRecordIO.read_idx)
long rio_read_at(void* h, long pos, const uint8_t** data) {
  Reader* r = static_cast<Reader*>(h);
  if (std::fseek(r->f, pos, SEEK_SET) != 0) return -2;
  return rio_read(h, data);
}

void rio_seek(void* h, long pos) {
  Reader* r = static_cast<Reader*>(h);
  std::fseek(r->f, pos, SEEK_SET);
}

long rio_tell(void* h) {
  Reader* r = static_cast<Reader*>(h);
  return std::ftell(r->f);
}

const char* rio_error(void* h) {
  return static_cast<Reader*>(h)->error.c_str();
}

void rio_close(void* h) { delete static_cast<Reader*>(h); }

void* rio_open_prefetch(const char* path, int queue_size) {
  Prefetcher* p = new Prefetcher(path, queue_size);
  if (!p->reader.f) {
    delete p;
    return nullptr;
  }
  return p;
}

long rio_pf_read(void* h, const uint8_t** data) {
  Prefetcher* p = static_cast<Prefetcher*>(h);
  int rc = p->Pop();
  if (rc == 0) return -1;
  if (rc < 0) return -2;
  *data = p->current.data();
  return static_cast<long>(p->current.size());
}

void rio_pf_close(void* h) { delete static_cast<Prefetcher*>(h); }

}  // extern "C"
