// predict_c.cpp — C predict API over mx.deploy artifacts.
//
// Reference analogue: src/c_api/c_predict_api.cc. The reference builds
// a GraphExecutor from symbol JSON + NDArray params; here the artifact
// already IS an executable program (StableHLO via jax.export with
// params baked in), so this file only has to (1) host a CPython
// interpreter, (2) hand the artifact to a tiny self-contained loader
// snippet that needs nothing beyond `jax` + `numpy`, and (3) marshal
// float buffers across the C boundary through the buffer protocol —
// no numpy C API, no mxnet_tpu import.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC predict_c.cpp \
//            $(python3-config --includes) \
//            -L$(python3-config --prefix)/lib -lpython3.X \
//            -o libmxtpu_predict.so

#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "../include/mxtpu_predict.h"

namespace {

thread_local char g_err[1024] = "";

void set_err(const char *fmt, const char *detail) {
  snprintf(g_err, sizeof(g_err), fmt, detail ? detail : "");
}

// Fetch + clear the pending Python exception into g_err (GIL held).
void set_err_from_python(const char *where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "<no exception>";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  snprintf(g_err, sizeof(g_err), "%s: %s", where, msg.c_str());
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// The loader lives entirely inside this snippet so the .so has no
// Python-side package dependency. Mirrors deploy.py's file format:
// b"MXTPUPRED1" + <u32 header_len> + json header + jax.export blob.
const char *kLoaderSrc = R"PY(
import json, struct

_MAGIC = b"MXTPUPRED1"

_plat_union = []  # platforms requested by every artifact loaded so far

def _sync_platforms(platforms):
    # Site hooks (e.g. an accelerator-plugin sitecustomize) can override
    # jax's platform selection at interpreter start, defeating
    # JAX_PLATFORMS in our env AND making any backend query initialize
    # an accelerator whose transport may be down (hanging this host
    # process). Re-pin the config: the env var wins; otherwise restrict
    # to the UNION of the platforms every loaded artifact needs (+cpu),
    # so loading a cpu artifact first does not lock a later tpu
    # artifact out of its backend.
    import os
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        want = os.environ["JAX_PLATFORMS"]
    else:
        for p in [q.lower() for q in platforms] + ["cpu"]:
            if p not in _plat_union:
                _plat_union.append(p)
        want = ",".join(_plat_union)
    try:
        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # backends already initialized: fall through to device pick

def _pick_device(platforms):
    # the artifact is platform-specific (StableHLO lowered per backend);
    # run it on a device matching its export platform, regardless of the
    # host process's default jax backend
    import jax
    want = {p.lower() for p in platforms}
    for name in ("tpu", "cuda", "rocm", "gpu", "cpu"):
        if name in want or (name in ("cuda", "rocm") and "gpu" in want):
            try:
                return jax.local_devices(backend=name)[0]
            except Exception:
                continue
    if "cpu" in want:
        return jax.local_devices(backend="cpu")[0]
    # a clear error beats the downstream "exported for X used on Y"
    raise RuntimeError(
        "artifact was exported for platforms %r but no matching jax "
        "device is available in this process (loaded-artifact platform "
        "union: %r)" % (sorted(want), _plat_union or ["<env-pinned>"]))

def load(path):
    import numpy as np
    from jax import export as jexport
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_MAGIC):
        raise ValueError("not an mxnet_tpu predictor artifact: %s" % path)
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from("<I", blob, off)
    off += 4
    meta = json.loads(blob[off:off + hlen].decode())
    exported = jexport.deserialize(blob[off + hlen:])
    plats = getattr(exported, "platforms", ("cpu",))
    _sync_platforms(plats)
    return {
        "meta": meta,
        "exported": exported,
        "shape": tuple(meta["input_shape"]),
        "dtype": meta["input_dtype"],
        "device": _pick_device(plats),
    }

def forward(pred, buf):
    import jax
    import numpy as np
    x = np.frombuffer(buf, dtype=np.float32).reshape(pred["shape"])
    x = x.astype(pred["dtype"], copy=False)
    outs = pred["exported"].call(jax.device_put(x, pred["device"]))
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return [np.ascontiguousarray(np.asarray(o), dtype=np.float32)
            for o in outs]
)PY";

struct Predictor {
  PyObject *pred = nullptr;     // dict returned by load()
  PyObject *forward = nullptr;  // loader forward()
  PyObject *outputs = nullptr;  // list of float32 ndarrays (last Forward)
  std::vector<int64_t> input_shape;
  std::vector<std::vector<int64_t>> out_shapes;
};

PyObject *g_loader_ns = nullptr;  // module namespace holding load/forward

class GIL {
 public:
  GIL() : state_(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

std::mutex g_init_mutex;

// Initialize the interpreter (if this process doesn't already host
// one) and compile the loader snippet once. Returns false + g_err on
// failure. Caller must NOT hold the GIL. The mutex makes concurrent
// first MXTpuPredCreate calls safe (the header allows one handle per
// thread): without it two threads could both see Py_IsInitialized()
// false and race Py_InitializeFromConfig.
bool ensure_loader() {
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (!Py_IsInitialized()) {
    PyConfig config;
    PyConfig_InitPythonConfig(&config);
    config.install_signal_handlers = 0;  // stay out of the host's way
    PyStatus status = Py_InitializeFromConfig(&config);
    PyConfig_Clear(&config);
    if (PyStatus_Exception(status)) {
      set_err("interpreter init failed: %s",
              status.err_msg ? status.err_msg : "");
      return false;
    }
    // Py_InitializeFromConfig leaves us holding the GIL; drop to a
    // known state so every entry point can use PyGILState_Ensure.
    PyEval_SaveThread();
  }
  GIL gil;
  if (g_loader_ns == nullptr) {
    PyObject *mod = PyModule_New("_mxtpu_c_loader");
    PyObject *ns = mod ? PyModule_GetDict(mod) : nullptr;
    if (ns == nullptr ||
        PyDict_SetItemString(ns, "__builtins__", PyEval_GetBuiltins()) != 0) {
      set_err_from_python("loader namespace");
      Py_XDECREF(mod);
      return false;
    }
    PyObject *r = PyRun_String(kLoaderSrc, Py_file_input, ns, ns);
    if (r == nullptr) {
      set_err_from_python("loader compile");
      Py_DECREF(mod);
      return false;
    }
    Py_DECREF(r);
    g_loader_ns = mod;  // keep the module (and its dict) alive forever
  }
  return true;
}

bool fill_shape(PyObject *ndarray, std::vector<int64_t> *out) {
  Py_buffer view;
  if (PyObject_GetBuffer(ndarray, &view,
                         PyBUF_CONTIG_RO | PyBUF_FORMAT) != 0) {
    set_err_from_python("output buffer");
    return false;
  }
  out->assign(view.shape, view.shape + view.ndim);
  PyBuffer_Release(&view);
  return true;
}

}  // namespace

extern "C" int MXTpuPredCreate(const char *artifact_path,
                               MXTpuPredictorHandle *out) {
  if (out == nullptr || artifact_path == nullptr) {
    set_err("null argument%s", nullptr);
    return -1;
  }
  *out = nullptr;
  if (!ensure_loader()) return -1;
  GIL gil;
  PyObject *ns = PyModule_GetDict(g_loader_ns);
  PyObject *load = PyDict_GetItemString(ns, "load");          // borrowed
  PyObject *forward = PyDict_GetItemString(ns, "forward");    // borrowed
  PyObject *pred =
      PyObject_CallFunction(load, "s", artifact_path);        // new
  if (pred == nullptr) {
    set_err_from_python("load");
    return -1;
  }
  auto *p = new Predictor;
  p->pred = pred;
  p->forward = forward;
  Py_INCREF(p->forward);
  PyObject *shape = PyDict_GetItemString(pred, "shape");      // borrowed
  Py_ssize_t n = PyTuple_Size(shape);
  for (Py_ssize_t i = 0; i < n; ++i)
    p->input_shape.push_back(PyLong_AsLongLong(PyTuple_GetItem(shape, i)));
  *out = p;
  return 0;
}

extern "C" int MXTpuPredGetInputShape(MXTpuPredictorHandle h,
                                      const int64_t **shape, int *ndim) {
  auto *p = static_cast<Predictor *>(h);
  if (p == nullptr) {
    set_err("null handle%s", nullptr);
    return -1;
  }
  *shape = p->input_shape.data();
  *ndim = static_cast<int>(p->input_shape.size());
  return 0;
}

extern "C" int MXTpuPredForward(MXTpuPredictorHandle h, const float *data,
                                size_t size) {
  auto *p = static_cast<Predictor *>(h);
  if (p == nullptr || data == nullptr) {
    set_err("null handle/data%s", nullptr);
    return -1;
  }
  int64_t want = 1;
  for (int64_t d : p->input_shape) want *= d;
  if (static_cast<int64_t>(size) != want) {
    set_err("input size mismatch%s", nullptr);
    return -1;
  }
  GIL gil;
  PyObject *buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data), size * sizeof(float));
  if (buf == nullptr) {
    set_err_from_python("input alloc");
    return -1;
  }
  PyObject *outs = PyObject_CallFunctionObjArgs(p->forward, p->pred, buf,
                                                nullptr);
  Py_DECREF(buf);
  if (outs == nullptr) {
    set_err_from_python("forward");
    return -1;
  }
  // stage shapes fully before publishing: a mid-loop failure must leave
  // the handle's previous outputs/shapes consistent, not half-swapped
  std::vector<std::vector<int64_t>> shapes;
  Py_ssize_t n = PyList_Size(outs);
  for (Py_ssize_t i = 0; i < n; ++i) {
    std::vector<int64_t> s;
    if (!fill_shape(PyList_GetItem(outs, i), &s)) {
      Py_DECREF(outs);
      return -1;
    }
    shapes.push_back(std::move(s));
  }
  Py_XDECREF(p->outputs);
  p->outputs = outs;
  p->out_shapes = std::move(shapes);
  return 0;
}

extern "C" int MXTpuPredGetNumOutputs(MXTpuPredictorHandle h, int *num) {
  auto *p = static_cast<Predictor *>(h);
  if (p == nullptr || p->outputs == nullptr) {
    set_err("no outputs (call Forward first)%s", nullptr);
    return -1;
  }
  GIL gil;
  *num = static_cast<int>(PyList_Size(p->outputs));
  return 0;
}

extern "C" int MXTpuPredGetOutputShape(MXTpuPredictorHandle h, unsigned index,
                                       const int64_t **shape, int *ndim) {
  auto *p = static_cast<Predictor *>(h);
  if (p == nullptr || index >= p->out_shapes.size()) {
    set_err("bad output index%s", nullptr);
    return -1;
  }
  *shape = p->out_shapes[index].data();
  *ndim = static_cast<int>(p->out_shapes[index].size());
  return 0;
}

extern "C" int MXTpuPredGetOutput(MXTpuPredictorHandle h, unsigned index,
                                  float *data, size_t size) {
  auto *p = static_cast<Predictor *>(h);
  if (p == nullptr || p->outputs == nullptr) {
    set_err("no outputs (call Forward first)%s", nullptr);
    return -1;
  }
  GIL gil;
  if (index >= static_cast<size_t>(PyList_Size(p->outputs))) {
    set_err("bad output index%s", nullptr);
    return -1;
  }
  Py_buffer view;
  if (PyObject_GetBuffer(PyList_GetItem(p->outputs, index), &view,
                         PyBUF_CONTIG_RO) != 0) {
    set_err_from_python("output buffer");
    return -1;
  }
  if (static_cast<size_t>(view.len) != size * sizeof(float)) {
    PyBuffer_Release(&view);
    set_err("output size mismatch%s", nullptr);
    return -1;
  }
  memcpy(data, view.buf, view.len);
  PyBuffer_Release(&view);
  return 0;
}

extern "C" const char *MXTpuPredGetLastError(void) { return g_err; }

extern "C" void MXTpuPredFree(MXTpuPredictorHandle h) {
  auto *p = static_cast<Predictor *>(h);
  if (p == nullptr) return;
  if (Py_IsInitialized()) {
    GIL gil;
    Py_XDECREF(p->pred);
    Py_XDECREF(p->forward);
    Py_XDECREF(p->outputs);
  }
  delete p;
}
