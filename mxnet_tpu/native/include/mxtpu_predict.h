/* mxtpu_predict.h — embed-from-C inference over mx.deploy artifacts.
 *
 * Reference analogue: include/mxnet/c_predict_api.h (MXPredCreate /
 * MXPredSetInput / MXPredForward / MXPredGetOutput). The reference
 * loads a symbol-JSON + param blob into its own C++ executor; the
 * TPU-native artifact is a serialized StableHLO program with params
 * baked in (see mxnet_tpu/deploy.py), executed by JAX. This shim
 * embeds a CPython interpreter so a plain C/C++ host — no Python code
 * written by the user — can run that artifact. The embedded
 * interpreter needs only `jax` + `numpy` importable, not mxnet_tpu,
 * mirroring the reference amalgamation story (framework-free serving).
 *
 * All functions return 0 on success, -1 on failure;
 * MXTpuPredGetLastError() describes the most recent failure.
 * Handles are NOT thread-safe; create one per thread (the reference's
 * MXPredCreateMultiThread contract) — the shim serializes interpreter
 * access through the GIL internally.
 */
#ifndef MXTPU_PREDICT_H_
#define MXTPU_PREDICT_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *MXTpuPredictorHandle;

/* Load a .mxtpu artifact (written by mx.deploy.export_predictor).
 * Initializes the embedded interpreter on first use. */
int MXTpuPredCreate(const char *artifact_path, MXTpuPredictorHandle *out);

/* Input geometry, parsed from the artifact header.
 * *shape points at handle-owned memory, valid until MXTpuPredFree. */
int MXTpuPredGetInputShape(MXTpuPredictorHandle h, const int64_t **shape,
                           int *ndim);

/* Run the program on `size` floats (must equal the input element
 * count; the artifact's own dtype conversion is applied inside). */
int MXTpuPredForward(MXTpuPredictorHandle h, const float *data, size_t size);

/* Number of outputs of the last Forward. */
int MXTpuPredGetNumOutputs(MXTpuPredictorHandle h, int *num);

/* Shape of output `index` from the last Forward; handle-owned memory,
 * valid until the next Forward or Free. */
int MXTpuPredGetOutputShape(MXTpuPredictorHandle h, unsigned index,
                            const int64_t **shape, int *ndim);

/* Copy output `index` (as float32) into caller memory of `size`
 * elements; `size` must equal the output element count. */
int MXTpuPredGetOutput(MXTpuPredictorHandle h, unsigned index, float *data,
                       size_t size);

/* Last error message (thread-local static buffer, never NULL). */
const char *MXTpuPredGetLastError(void);

void MXTpuPredFree(MXTpuPredictorHandle h);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_PREDICT_H_ */
