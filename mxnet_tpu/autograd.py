"""Autograd: tape-based reverse-mode AD over ``jax.vjp``.

TPU-native re-design of the reference's imperative autograd
(reference: src/imperative/imperative.cc:204 ``RecordOp``, :376 ``Backward``;
python/mxnet/autograd.py). The reference tapes nnvm nodes and then builds a
gradient *graph* with the MXGradient pass; here each recorded op eagerly
captures its ``jax.vjp`` closure (XLA keeps the residuals on-device) and
``backward()`` walks the tape in reverse — no graph pass needed, XLA already
compiled each primal/adjoint pair.

The recording/train-mode scopes mirror the reference API exactly:
``record()``, ``pause()``, ``train_mode()``, ``predict_mode()``,
``mark_variables()``, ``backward()``, ``grad()``.
"""
from __future__ import annotations

import itertools
import threading
import weakref
from typing import Dict, List, Optional

import numpy as _np
import jax

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording",
    "is_training", "set_recording", "set_training", "mark_variables",
    "backward", "grad",
]

# ---------------------------------------------------------------- state ----

# Autograd mode and tape are THREAD-LOCAL, like the reference's
# thread_local imperative state (reference: src/imperative/imperative.h
# is_train_/is_recording_ are thread_local): a trace or a pause() in one
# serving thread must not flip recording off for a training thread, and
# concurrent recorders each get their own graph.
class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape = _Tape()


_SLOT = itertools.count()
_SEQ = itertools.count()

# leaf slot -> (weakref to NDArray, grad_req). PROCESS-global, unlike the
# per-thread graph: attach_grad() commonly runs on the main thread while
# backward() runs in a worker (GIL-atomic dict ops; entries die with the
# array weakref).
_LEAVES: Dict[int, tuple] = {}


class _Tape:
    def __init__(self):
        self.nodes: List["_Node"] = []
        self.slot_producer: Dict[int, "_Node"] = {}

    def clear_graph(self):
        self.nodes = []
        self.slot_producer = {}

    def drop_nodes(self, node_ids):
        """Drop only the given nodes (post-backward cleanup of the traversed
        subgraph — other recorded-but-not-yet-backpropagated heads in the
        same scope stay differentiable, matching the reference)."""
        self.nodes = [n for n in self.nodes if id(n) not in node_ids]
        self.slot_producer = {s: n for s, n in self.slot_producer.items()
                              if id(n) not in node_ids}


_STATE = _AGState()


def _tape() -> "_Tape":
    return _STATE.tape


class _Node:
    """One recorded op application.

    ``fn``/``xs`` (the primal function and its inputs) are kept so
    ``create_graph=True`` can re-derive the vjp *differentiably*: the
    captured ``vjp_fn`` closure bakes its residuals as constants, so
    taping only cotangent flow would lose d(grad)/d(input); re-running
    ``jax.vjp(fn, *xs)`` inside a taped application keeps it."""

    __slots__ = ("seq", "vjp_fn", "in_slots", "out_slots", "out_avals",
                 "fn", "xs")

    def __init__(self, vjp_fn, in_slots, out_slots, out_avals, fn=None,
                 xs=None):
        self.seq = next(_SEQ)
        self.vjp_fn = vjp_fn
        self.in_slots = in_slots      # per input: slot int or None (no grad)
        self.out_slots = out_slots
        self.out_avals = out_avals    # (shape, dtype) per output
        self.fn = fn
        self.xs = xs


def new_slot() -> int:
    return next(_SLOT)


def register_leaf(slot: int, array, grad_req: str):
    _LEAVES[slot] = (weakref.ref(array), grad_req)


def record_node(vjp_fn, in_slots, out_slots, out_avals, fn=None,
                xs=None) -> _Node:
    node = _Node(vjp_fn, in_slots, out_slots, out_avals, fn=fn, xs=xs)
    tape = _tape()
    tape.nodes.append(node)
    for s in out_slots:
        tape.slot_producer[s] = node
    return node


# ------------------------------------------------------------- scopes ------

class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        self._old = (_STATE.recording, _STATE.training)
        if self._rec and not _STATE.recording:
            # entering a fresh outermost recording scope: the previous
            # iteration's graph (if any survived without a backward) is
            # unreachable by user code now — drop it so vjp residuals don't
            # pin HBM across training iterations.
            _tape().clear_graph()
        if self._rec is not None:
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        _STATE.recording, _STATE.training = self._old
        return False


def record(train_mode: bool = True) -> _Scope:
    """Scope in which executed ops land on the autograd tape
    (reference: python/mxnet/autograd.py record)."""
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(is_record: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, is_record
    return prev


def set_training(train: bool) -> bool:
    prev, _STATE.training = _STATE.training, train
    return prev


# ------------------------------------------------------------ backward -----

def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference API parity)."""
    if not isinstance(variables, (list, tuple)):
        variables, gradients = [variables], [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v.attach_grad(grad_req=req)
        if g is not None:
            v._grad = g


def _zero_cotangent(shape, dtype):
    d = _np.dtype(dtype)
    if _np.issubdtype(d, _np.inexact) or d.name == "bfloat16" or d.kind == "V":
        import jax.numpy as jnp
        return jnp.zeros(shape, dtype)
    return _np.zeros(shape, jax.dtypes.float0)


def _run_backward(heads, head_grads, retain_graph, create_graph=False):
    """Reverse-walk the tape from ``heads``; returns {slot: grad}.

    With ``create_graph=True`` each vjp application is routed back
    through the op-invoke chokepoint, so the gradient computation itself
    lands on the tape and can be differentiated again (the reference
    builds a differentiable grad graph via the MXGradient pass,
    src/imperative/imperative.cc:376)."""
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray  # local import: avoids cycle

    if not isinstance(heads, (list, tuple)):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    grads: Dict[int, object] = {}

    from .ndarray.sparse import RowSparseNDArray, add as _sparse_add

    def acc(old, new):
        if old is None:
            return new
        so = isinstance(old, RowSparseNDArray)
        sn = isinstance(new, RowSparseNDArray)
        if so and sn:             # stays row-sparse: concat indices/values
            return _sparse_add(old, new)
        if so:
            old = old._data       # mixed: fall back to dense accumulation
        if sn:
            new = new._data
        if create_graph and (isinstance(old, NDArray)
                             or isinstance(new, NDArray)):
            a = old if isinstance(old, NDArray) else NDArray(old)
            b = new if isinstance(new, NDArray) else NDArray(new)
            return a + b          # taped add: accumulation differentiable
        return old + new

    roots = []
    tape = _tape()
    for h, hg in zip(heads, head_grads):
        slot = getattr(h, "_ag_slot", None)
        if slot is None:
            raise ValueError(
                "cannot differentiate a head that was not computed inside "
                "autograd.record() (reference: Imperative::Backward check)")
        g = (jnp.ones(h.shape, h.dtype) if hg is None
             else (hg._data if isinstance(hg, NDArray) else jnp.asarray(hg)))
        grads[slot] = acc(grads.get(slot), g)
        prod = tape.slot_producer.get(slot)
        if prod is not None:
            roots.append(prod)

    # reachable set (walk producers backwards)
    reachable = set()
    stack = list(roots)
    while stack:
        node = stack.pop()
        if id(node) in reachable:
            continue
        reachable.add(id(node))
        for s in node.in_slots:
            if s is not None:
                p = tape.slot_producer.get(s)
                if p is not None and id(p) not in reachable:
                    stack.append(p)

    ordered = sorted((n for n in tape.nodes if id(n) in reachable),
                     key=lambda n: n.seq, reverse=True)
    for node in ordered:
        cots = tuple(
            grads.get(s) if s in grads else _zero_cotangent(*aval)
            for s, aval in zip(node.out_slots, node.out_avals))
        if create_graph and node.fn is None:
            raise NotImplementedError(
                "create_graph=True reached a tape node recorded without "
                "its primal function; higher-order gradients are not "
                "available through this op")
        if create_graph and node.fn is not None:
            in_grads = _taped_vjp(node, cots)
        else:
            cots = tuple(c._data if isinstance(c, NDArray) else c
                         for c in cots)
            in_grads = node.vjp_fn(cots if len(cots) > 1 else cots[0])
        for s, g in zip(node.in_slots, in_grads):
            if s is None or g is None or (hasattr(g, "dtype")
                                          and g.dtype == jax.dtypes.float0):
                continue
            grads[s] = acc(grads.get(s), g)

    if not retain_graph:
        tape.drop_nodes(reachable)
    return grads


def _taped_vjp(node, cots):
    """Apply a node's vjp THROUGH the invoke chokepoint so the
    application is itself recorded. The node's saved primal inputs
    re-enter with their original slots, so second-order gradients flow
    to them (the vjp closure's residuals alone would be constants)."""
    from .ndarray.ndarray import NDArray
    from .ops.invoke import apply_fn

    n_in = len(node.in_slots)
    multi = len(node.out_slots) > 1

    def vjp_apply(*args):
        xs, cs = args[:n_in], args[n_in:]
        _, vjp = jax.vjp(node.fn, *xs)
        gs = vjp(tuple(cs) if multi else cs[0])
        return gs[0] if n_in == 1 else tuple(gs)

    x_nds = []
    for x, s in zip(node.xs, node.in_slots):
        nd_x = NDArray(x)
        if s is not None:
            nd_x._ag_slot = s
        x_nds.append(nd_x)
    cot_args = [c if isinstance(c, NDArray) or not hasattr(c, "shape")
                else (c if c.dtype == jax.dtypes.float0 else NDArray(c))
                for c in cots]
    out = apply_fn(vjp_apply, x_nds + list(cot_args))
    return (out,) if n_in == 1 else tuple(out)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of ``heads`` w.r.t. all attached variables and
    store them in each variable's ``.grad`` (reference:
    python/mxnet/autograd.py backward → MXAutogradBackwardEx)."""
    grads = _run_backward(heads, head_grads, retain_graph)
    from .ndarray.ndarray import NDArray
    for slot, (ref, req) in list(_LEAVES.items()):
        arr = ref()
        if arr is None:
            _LEAVES.pop(slot, None)
            continue
        if slot in grads and req != "null":
            g = grads[slot]
            from .ndarray.sparse import RowSparseNDArray, add as _sp_add
            if isinstance(g, RowSparseNDArray):
                if req == "add" and isinstance(arr._grad, RowSparseNDArray):
                    arr._grad = _sp_add(arr._grad, g)
                elif req == "add" and arr._grad is not None:
                    arr._grad = NDArray(arr._grad._data + g._data)
                else:
                    arr._grad = g
            elif req == "add" and arr._grad is not None:
                arr._grad = NDArray(arr._grad._data + g)
            else:
                arr._grad = NDArray(g)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of ``heads`` w.r.t. ``variables`` without touching
    ``.grad`` buffers (reference: python/mxnet/autograd.py grad).

    ``create_graph=True`` records the gradient computation itself, so the
    returned arrays can be differentiated again — same contract as the
    reference (python/mxnet/autograd.py:271, used by
    tests/python/unittest/test_higher_order_grad.py)."""
    single = not isinstance(variables, (list, tuple))
    vars_ = [variables] if single else list(variables)
    if retain_graph is None:
        retain_graph = create_graph
    if create_graph:
        # the vjp applications must land on the tape; the graph is
        # retained by default (needed for the next-order backward) but an
        # explicit retain_graph=False is honored
        prev = set_recording(True)
        try:
            grads = _run_backward(heads, head_grads, retain_graph,
                                  create_graph=True)
        finally:
            set_recording(prev)
    else:
        grads = _run_backward(heads, head_grads, retain_graph)
    from .ndarray.ndarray import NDArray
    import jax.numpy as jnp
    out = []
    for v in vars_:
        slot = getattr(v, "_ag_slot", None)
        if slot is None or slot not in grads:
            out.append(NDArray(jnp.zeros(v.shape, v.dtype)))
        else:
            g = grads[slot]
            out.append(g if isinstance(g, NDArray) else NDArray(g))
    return out[0] if single else out


def get_symbol(x):  # reference API parity; graph introspection n/a here
    raise NotImplementedError("autograd.get_symbol is not supported on the "
                              "TPU backend (no nnvm graph); use Symbol API")
