"""Checkpoint helpers + legacy FeedForward.

Reference: python/mxnet/model.py (save_checkpoint :403, load_checkpoint
:452, FeedForward). Checkpoints keep the reference's file naming and key
conventions (``prefix-symbol.json`` + ``prefix-NNNN.params`` with
``arg:``/``aux:`` key prefixes), but the .params container itself is this
repo's MXTPU1 binary format (ndarray/__init__.py), NOT the reference's
C++ NDArray serialisation — reference-produced .params files cannot be
loaded directly and vice versa.
"""
from __future__ import annotations

from .ndarray import NDArray, save as nd_save, load as nd_load

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "BatchEndParam", "FeedForward"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """reference: model.py:403. The ``.params`` write is atomic
    (``nd_save`` goes through resilience.atomic): a crash mid-save
    leaves the previous epoch's file intact, never a torn one. Returns
    the nd_save metadata (file/array CRCs) for manifest use."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    return nd_save(param_name, save_dict)


def load_params(prefix, epoch):
    """reference: model.py:429.

    Malformed containers raise ``mxnet_tpu.error.CheckpointCorruptError``
    (from ``nd_load``); keys without the ``arg:``/``aux:`` convention
    raise ``mxnet_tpu.error.InternalError`` naming the key and file —
    never silently dropped, never a bare KeyError/ValueError."""
    from . import error
    fname = f"{prefix}-{epoch:04d}.params"
    save_dict = nd_load(fname)
    if not isinstance(save_dict, dict):
        raise error.InternalError(
            f"'{fname}': contains unnamed arrays — not a checkpoint "
            "saved by save_checkpoint")
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if not _ or tp not in ("arg", "aux"):
            raise error.InternalError(
                f"'{fname}': key '{k}' has no 'arg:'/'aux:' prefix — "
                "file was not produced by save_checkpoint or is corrupt")
        (arg_params if tp == "arg" else aux_params)[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """reference: model.py:452."""
    from .symbol import load as sym_load
    symbol = sym_load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


class FeedForward:
    """Oldest-generation model API (reference: model.py:551) — kept as a
    thin veneer over Module for script compatibility."""

    def __init__(self, symbol, ctx=None, num_epoch=None,
                 epoch_size=None, optimizer="sgd",
                 initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.numpy_batch_size = numpy_batch_size
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        from .module import Module
        from .io.io import NDArrayIter
        from . import initializer as init_mod
        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, y, batch_size=self.numpy_batch_size)
        label_names = [n for n in self.symbol.list_arguments()
                       if n.endswith("label")]
        mod = Module(self.symbol,
                     data_names=[d.name for d in X.provide_data],
                     label_names=label_names)
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs.get(
                    "optimizer_params", (("learning_rate", 0.01),)),
                initializer=self.initializer or init_mod.Uniform(0.01),
                arg_params=self.arg_params, aux_params=self.aux_params,
                num_epoch=self.num_epoch, begin_epoch=self.begin_epoch)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from .io.io import NDArrayIter
        if not hasattr(X, "provide_data"):
            X = NDArrayIter(X, batch_size=self.numpy_batch_size)
        out = self._module.predict(X, num_batch=num_batch, reset=reset)
        return out.asnumpy() if isinstance(out, NDArray) else out

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None
                        else self.num_epoch, self.symbol,
                        self.arg_params, self.aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)
