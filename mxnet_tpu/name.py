"""mx.name — NameManager / Prefix scopes for symbol naming.

Reference: python/mxnet/name.py (NameManager:25 auto-names symbols
op0, op1, ...; Prefix:74 prepends a fixed prefix). The Symbol layer
consults the active manager when no explicit ``name=`` is given.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]

_TLS = threading.local()


def _stack():
    if not hasattr(_TLS, "stack"):
        _TLS.stack = []
    return _TLS.stack


class NameManager:
    """Auto-naming scope (reference: name.py:25)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


class Prefix(NameManager):
    """Prefixing scope (reference: name.py:74):
    ``with mx.name.Prefix('stage1_'):``."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current():
    stack = _stack()
    if not stack:
        if not hasattr(_TLS, "default"):
            _TLS.default = NameManager()
        return _TLS.default
    return stack[-1]
