"""mx.monitor — training-time tensor inspection.

Reference: python/mxnet/monitor.py:32 (Monitor installs a stat callback
on every executor output and prints aggregated stats per step). Here the
same surface rides the Block forward hooks: ``install(block)`` hooks a
block tree, ``tic()``/``toc()`` bracket a step, and ``toc_print()``
prints ``(step, name, stat)`` rows. The default stat is the reference's
|x|/size norm.
"""
from __future__ import annotations

import logging
import re

import numpy as _np

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval=1, stat_func=None, pattern=".*",
                 sort=False):
        self.interval = int(interval)
        self.stat_func = stat_func or (
            lambda x: _np.abs(x).sum() / x.size)   # reference default
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._handles = []

    # -- installation ------------------------------------------------------
    def install(self, block):
        """Hook a Block (and all children) so forward outputs are
        recorded while activated (reference: Monitor.install wraps the
        executor's monitor_callback)."""
        for name, child in self._walk(block):
            h = child.register_forward_hook(
                lambda blk, args, out, _n=name: self._record(_n, out))
            self._handles.append(h)
        return self

    def _walk(self, block, prefix=""):
        yield (prefix + (block.name or block.__class__.__name__), block)
        for cname, child in getattr(block, "_children", {}).items():
            yield from self._walk(child, prefix + cname + ".")

    def _record(self, name, out):
        if not self.activated or not self.re_pattern.match(name):
            return
        outs = out if isinstance(out, (list, tuple)) else [out]
        for i, o in enumerate(outs):
            try:
                arr = o.asnumpy()
            except AttributeError:
                continue
            key = name if len(outs) == 1 else f"{name}_output{i}"
            self.queue.append((self.step, key, self.stat_func(arr)))

    # -- step bracketing ---------------------------------------------------
    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []

    def toc(self):
        """Deactivate and return the collected (step, name, stat) rows."""
        if not self.activated:
            self.step += 1
            return []
        self.activated = False
        res = list(self.queue)
        if self.sort:
            res.sort(key=lambda r: r[1])
        self.queue = []
        self.step += 1
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, stat)
