"""mx.engine — execution-engine controls (compatibility surface).

Reference: python/mxnet/engine.py (bulk/set_bulk_size batching of
engine ops to amortize dependency-tracking overhead). There is no
dependency engine here: JAX async dispatch queues work and XLA fuses
whole programs, so bulking is inherent. The API is kept so reference
training loops (`with mx.engine.bulk(64):`) run unchanged as no-ops.
"""
from contextlib import contextmanager

__all__ = ["bulk", "set_bulk_size"]

_BULK_SIZE = 15


def set_bulk_size(size):
    """Set the bulk size (reference: engine.py:49). Returns the
    previous value; advisory only on this backend."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextmanager
def bulk(size):
    """Bulk scope (reference: engine.py:91) — a no-op context: XLA
    already executes each jitted step as one fused program."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
