"""mx.library — load external operator libraries (plugins).

Reference: python/mxnet/library.py (MXLoadLib) + include/mxnet/lib_api.h:
user-compiled shared libraries register custom operators into the
running framework. The TPU build keeps the capability with a simpler C
ABI (lib_api.h's 4k-line header exists to marshal NDArrays through the
engine; here host ops marshal plain buffers through ctypes and run as
jax host callbacks, so a plugin is a handful of exported symbols):

  // number of ops in the library
  int mxtpu_num_ops(void);
  // name of op i (NUL-terminated, static storage)
  const char* mxtpu_op_name(int i);
  // compute: inputs/outputs as float32 buffers.
  //   in/out descriptors: n_arrays, per-array (data*, ndim, shape*)
  //   returns 0 on success
  int mxtpu_op_compute(int i,
                       int n_in, const float** in, const int* in_ndim,
                       const long* const* in_shape,
                       float* out, const long* out_shape, int out_ndim);
  // output shape inference: writes out_shape/out_ndim from input
  // shapes; out_shape has room for 8 dims (MXTPU_MAX_NDIM)
  int mxtpu_op_infer_shape(int i,
                           int n_in, const int* in_ndim,
                           const long* const* in_shape,
                           long* out_shape, int* out_ndim);

Loaded ops register under their exported names as host ops (CPU
callback), callable from nd/sym/gluon like any other operator. See
tests/test_library.py for a complete C++ plugin built with g++.
"""
from __future__ import annotations

import ctypes
import os

import numpy as _np

__all__ = ["load", "loaded_libraries"]

_LOADED = {}


def loaded_libraries():
    return dict(_LOADED)


def _bind(lib):
    lib.mxtpu_num_ops.restype = ctypes.c_int
    lib.mxtpu_op_name.restype = ctypes.c_char_p
    lib.mxtpu_op_name.argtypes = [ctypes.c_int]
    lib.mxtpu_op_compute.restype = ctypes.c_int
    lib.mxtpu_op_infer_shape.restype = ctypes.c_int


def load(path, verbose=True):
    """Load an operator library and register its ops (reference:
    library.py:29 load). Returns the list of registered op names."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise OSError(f"library not found: {path}")
    lib = ctypes.CDLL(path)
    for sym in ("mxtpu_num_ops", "mxtpu_op_name", "mxtpu_op_compute",
                "mxtpu_op_infer_shape"):
        if not hasattr(lib, sym):
            raise OSError(
                f"{path} does not export {sym!r}; not an mxnet_tpu op "
                "library (see mxnet_tpu/library.py for the ABI)")
    _bind(lib)

    from .ops.registry import _REGISTRY, Operator

    names = []
    for i in range(lib.mxtpu_num_ops()):
        name = lib.mxtpu_op_name(i).decode()
        _REGISTRY[name] = Operator(name, _make_impl(lib, i, name),
                                   host_op=True, differentiable=False)
        names.append(name)
    # expose the new ops on the nd namespace
    from . import ndarray as _nd
    from .ndarray.register import make_op_func
    for name in names:
        setattr(_nd, name, make_op_func(_REGISTRY[name]))
    _LOADED[path] = names
    if verbose:
        print(f"loaded library {path!r}: ops {names}")
    return names


def _make_impl(lib, index, name):
    import jax

    def infer(shapes):
        n = len(shapes)
        ndims = (ctypes.c_int * n)(*[len(s) for s in shapes])
        shape_arrs = [(ctypes.c_long * len(s))(*s) for s in shapes]
        shape_ptrs = (ctypes.POINTER(ctypes.c_long) * n)(
            *[ctypes.cast(a, ctypes.POINTER(ctypes.c_long))
              for a in shape_arrs])
        out_shape = (ctypes.c_long * 8)()          # MXTPU_MAX_NDIM
        out_ndim = ctypes.c_int()
        rc = lib.mxtpu_op_infer_shape(index, n, ndims, shape_ptrs,
                                      out_shape, ctypes.byref(out_ndim))
        if rc != 0:
            raise RuntimeError(f"{name}: infer_shape failed ({rc})")
        if not 0 <= out_ndim.value <= 8:
            raise RuntimeError(
                f"{name}: infer_shape wrote out_ndim={out_ndim.value}; "
                "the ABI caps outputs at 8 dims")
        return tuple(out_shape[j] for j in range(out_ndim.value))

    def host_compute(*arrays):
        arrays = [_np.ascontiguousarray(_np.asarray(a, _np.float32))
                  for a in arrays]
        out_shape = infer([a.shape for a in arrays])
        out = _np.zeros(out_shape, _np.float32)
        n = len(arrays)
        ptrs = (ctypes.POINTER(ctypes.c_float) * n)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays])
        shape_arrs = [(ctypes.c_long * a.ndim)(*a.shape)
                      for a in arrays]
        shape_ptrs = (ctypes.POINTER(ctypes.c_long) * n)(
            *[ctypes.cast(s, ctypes.POINTER(ctypes.c_long))
              for s in shape_arrs])
        oshape = (ctypes.c_long * out.ndim)(*out.shape)
        rc = lib.mxtpu_op_compute(
            index, n, ptrs, ndims, shape_ptrs,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), oshape,
            out.ndim)
        if rc != 0:
            raise RuntimeError(f"{name}: compute failed ({rc})")
        return out

    def impl(*arrays, **kw):
        concrete = not any(isinstance(a, jax.core.Tracer)
                           for a in arrays)
        if concrete:
            import jax.numpy as jnp
            return jnp.asarray(host_compute(*[_np.asarray(a)
                                              for a in arrays]))
        out_shape = infer([tuple(a.shape) for a in arrays])
        return jax.pure_callback(
            host_compute, jax.ShapeDtypeStruct(out_shape, _np.float32),
            *arrays)

    return impl
