"""mx.onnx — ONNX export/import.

Reference: python/mxnet/contrib/onnx/ (mx2onnx + onnx2mx, ~8k LoC over
the onnx package). The TPU build ships its own minimal protobuf wire
codec (_proto.py), so models serialize to standard ONNX (opset 13)
without any onnx/protobuf dependency; the same codec powers the
importer, and tests roundtrip models through both.
"""
from .export import export_model  # noqa: F401
from .import_ import import_model  # noqa: F401
