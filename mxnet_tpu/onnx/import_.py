"""ONNX ModelProto bytes -> Symbol + params.

Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py + the
per-op mappings in _op_translations.py. Covers the same core set the
exporter emits, so export -> import roundtrips numerically.
"""
from __future__ import annotations

import numpy as _np

from . import _proto as P
from .export import (AT_FLOAT, AT_INT, AT_INTS, AT_STRING, TP_FLOAT,
                     TP_INT32, TP_INT64)

_DT_NP = {TP_FLOAT: _np.float32, TP_INT32: _np.int32, TP_INT64: _np.int64}


def _parse_attrs(node_msg):
    attrs = {}
    for raw in node_msg.get(5, []):
        a = P.decode(raw)
        name = a[1][0].decode()
        atype = a.get(20, [0])[0]
        if atype == AT_FLOAT:
            attrs[name] = a[2][0]
        elif atype == AT_INT:
            attrs[name] = a[3][0]
        elif atype == AT_STRING:
            attrs[name] = a[4][0].decode()
        elif atype == AT_INTS:
            ints = a.get(8, [])
            if len(ints) == 1 and isinstance(ints[0], bytes):
                ints = P.decode_packed_varints(ints[0])
            attrs[name] = [int(v) for v in ints]
        elif atype == 4:            # AttributeProto.TENSOR (field t=5)
            attrs[name] = a[5][0]   # raw TensorProto bytes
    return attrs


def _parse_tensor(raw):
    t = P.decode(raw)
    # proto3 packs repeated int64 dims by default (one bytes blob);
    # unpacked single-varint-per-field also appears in the wild
    dims = []
    for d in t.get(1, []):
        if isinstance(d, bytes):
            dims.extend(P.decode_packed_varints(d))
        else:
            dims.append(int(d))
    dt = _DT_NP[t.get(2, [TP_FLOAT])[0]]
    name = t.get(8, [b""])[0].decode()
    if 9 in t:                      # raw_data
        arr = _np.frombuffer(t[9][0], dt).reshape(dims)
    elif 4 in t:                    # float_data (packed or unpacked)
        vals = t[4]
        if vals and isinstance(vals[0], bytes):
            vals = _np.concatenate(
                [_np.frombuffer(v, "<f4") for v in vals])
        arr = _np.asarray(vals, _np.float32).reshape(dims)
    elif 7 in t:                    # int64_data (packed or unpacked)
        vals = t[7]
        if vals and isinstance(vals[0], bytes):
            vals = [v for b in vals for v in P.decode_packed_varints(b)]
        arr = _np.asarray(vals, _np.int64).reshape(dims)
    else:
        arr = _np.zeros(dims, dt)
    return name, arr


def import_model(model_bytes):
    """-> (sym, arg_params, aux_params) (reference:
    onnx2mx/import_model.py:32). Accepts bytes or a file path."""
    import mxnet_tpu as mx
    from ..ndarray import NDArray

    if isinstance(model_bytes, str):
        with open(model_bytes, "rb") as f:
            model_bytes = f.read()

    model = P.decode(model_bytes)
    graph = P.decode(model[7][0])

    inits = {}
    for raw in graph.get(5, []):
        name, arr = _parse_tensor(raw)
        inits[name] = arr

    values = {}          # onnx value name -> Symbol
    for raw in graph.get(11, []):   # graph inputs
        vi = P.decode(raw)
        name = vi[1][0].decode()
        if name not in inits:
            values[name] = mx.sym.var(name)

    arg_params, aux_params = {}, {}

    def sym_of(name):
        if name in values:
            return values[name]
        if name in inits:
            v = mx.sym.var(name)
            values[name] = v
            if name.endswith(("_moving_mean", "_moving_var",
                              "_running_mean", "_running_var")):
                aux_params[name] = NDArray(inits[name])
            else:
                arg_params[name] = NDArray(inits[name])
            return v
        raise KeyError(f"undefined ONNX value {name!r}")

    last = None
    for raw in graph.get(1, []):    # nodes, topologically ordered
        msg = P.decode(raw)
        ins = [v.decode() for v in msg.get(1, [])]
        outs = [v.decode() for v in msg.get(2, [])]
        name = msg.get(3, [b""])[0].decode()
        op = msg[4][0].decode()
        attrs = _parse_attrs(msg)
        last = _make(op, ins, outs, name, attrs, sym_of, values, inits)
    return last, arg_params, aux_params


def _make(op, ins, outs, name, attrs, sym_of, values, inits):
    import mxnet_tpu as mx

    if op == "Gemm":
        alpha = float(attrs.get("alpha", 1.0))
        beta = float(attrs.get("beta", 1.0))
        trans_a = bool(attrs.get("transA", 0))
        trans_b = bool(attrs.get("transB", 0))
        data = sym_of(ins[0])
        w = sym_of(ins[1])
        if (trans_b and not trans_a and alpha == 1.0
                and beta in (0.0, 1.0)):
            # the FullyConnected shape: y = x @ W^T (+ b)
            num_hidden = inits[ins[1]].shape[0]
            if len(ins) > 2 and beta == 1.0:
                out = mx.sym.FullyConnected(
                    data, w, sym_of(ins[2]), name=name,
                    num_hidden=num_hidden)
            else:
                out = mx.sym.FullyConnected(data, w, name=name,
                                            num_hidden=num_hidden,
                                            no_bias=True)
        else:
            # general Gemm from external exporters:
            # alpha*op(A)@op(B) + beta*C
            if trans_a:
                data = mx.sym.transpose(data, axes=(1, 0))
            if trans_b:
                w = mx.sym.transpose(w, axes=(1, 0))
            out = mx.sym.dot(data, w, name=name + "_mm")
            if alpha != 1.0:
                out = mx.sym._mul_scalar(out, scalar=alpha)
            if len(ins) > 2 and beta != 0.0:
                c = sym_of(ins[2])
                if beta != 1.0:
                    c = mx.sym._mul_scalar(c, scalar=beta)
                out = mx.sym.broadcast_add(out, c, name=name)
    elif op == "Conv":
        kwargs = dict(kernel=tuple(attrs["kernel_shape"]),
                      stride=tuple(attrs.get("strides", (1, 1))),
                      dilate=tuple(attrs.get("dilations", (1, 1))),
                      pad=tuple(attrs.get("pads", (0, 0, 0, 0))[:2]),
                      num_group=int(attrs.get("group", 1)),
                      num_filter=inits[ins[1]].shape[0], name=name)
        if len(ins) > 2:
            out = mx.sym.Convolution(sym_of(ins[0]), sym_of(ins[1]),
                                     sym_of(ins[2]), **kwargs)
        else:
            out = mx.sym.Convolution(sym_of(ins[0]), sym_of(ins[1]),
                                     no_bias=True, **kwargs)
    elif op in ("MaxPool", "AveragePool"):
        out = mx.sym.Pooling(
            sym_of(ins[0]), kernel=tuple(attrs["kernel_shape"]),
            stride=tuple(attrs.get("strides", (1, 1))),
            pad=tuple(attrs.get("pads", (0, 0, 0, 0))[:2]),
            pool_type="max" if op == "MaxPool" else "avg", name=name)
    elif op in ("GlobalMaxPool", "GlobalAveragePool"):
        out = mx.sym.Pooling(
            sym_of(ins[0]), kernel=(1, 1), global_pool=True,
            pool_type="max" if op == "GlobalMaxPool" else "avg",
            name=name)
    elif op == "BatchNormalization":
        out = mx.sym.BatchNorm(
            *[sym_of(i) for i in ins[:5]], name=name,
            eps=float(attrs.get("epsilon", 1e-5)),
            momentum=float(attrs.get("momentum", 0.9)),
            fix_gamma=False)
    elif op in ("Relu", "Sigmoid", "Tanh", "Softplus"):
        act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
               "Softplus": "softrelu"}[op]
        out = mx.sym.Activation(sym_of(ins[0]), act_type=act, name=name)
    elif op == "LeakyRelu":
        out = mx.sym.LeakyReLU(sym_of(ins[0]),
                               slope=float(attrs.get("alpha", 0.01)),
                               name=name)
    elif op == "Softmax":
        out = mx.sym.softmax(sym_of(ins[0]),
                             axis=int(attrs.get("axis", -1)), name=name)
    elif op == "Flatten":
        out = mx.sym.Flatten(sym_of(ins[0]), name=name)
    elif op == "Add":
        out = sym_of(ins[0]) + sym_of(ins[1])
    elif op == "Mul":
        out = sym_of(ins[0]) * sym_of(ins[1])
    elif op == "Sub":
        out = sym_of(ins[0]) - sym_of(ins[1])
    elif op == "Concat":
        out = mx.sym.Concat(*[sym_of(i) for i in ins],
                            dim=int(attrs.get("axis", 1)), name=name)
    elif op == "Reshape":
        shape = tuple(int(s) for s in inits[ins[1]])
        out = mx.sym.Reshape(sym_of(ins[0]), shape=shape, name=name)
    elif op == "Identity":
        out = sym_of(ins[0])
    elif op == "ConvTranspose":
        kwargs = dict(kernel=tuple(attrs["kernel_shape"]),
                      stride=tuple(attrs.get("strides", (1, 1))),
                      pad=tuple(attrs.get("pads", (0, 0, 0, 0))[:2]),
                      num_group=int(attrs.get("group", 1)),
                      name=name)
        if "output_padding" in attrs:
            kwargs["adj"] = tuple(attrs["output_padding"])
        w = inits[ins[1]]
        kwargs["num_filter"] = w.shape[1] * kwargs["num_group"]
        args = [sym_of(ins[0]), sym_of(ins[1])]
        if len(ins) > 2:
            args.append(sym_of(ins[2]))
        else:
            kwargs["no_bias"] = True
        out = mx.sym.Deconvolution(*args, **kwargs)
    elif op == "Transpose":
        out = mx.sym.transpose(sym_of(ins[0]),
                               axes=tuple(attrs.get("perm", ())),
                               name=name)
    elif op == "MatMul":
        out = mx.sym._npi_matmul(sym_of(ins[0]), sym_of(ins[1]),
                                 name=name)
    elif op == "LayerNormalization":
        out = mx.sym.LayerNorm(
            sym_of(ins[0]), sym_of(ins[1]), sym_of(ins[2]), name=name,
            axis=int(attrs.get("axis", -1)),
            eps=float(attrs.get("epsilon", 1e-5)))
    elif op == "InstanceNormalization":
        out = mx.sym.InstanceNorm(
            sym_of(ins[0]), sym_of(ins[1]), sym_of(ins[2]), name=name,
            eps=float(attrs.get("epsilon", 1e-3)))
    elif op in _UNARY_IMPORT:
        out = getattr(mx.sym, _UNARY_IMPORT[op])(sym_of(ins[0]),
                                                 name=name)
    elif op == "Div":
        out = sym_of(ins[0]) / sym_of(ins[1])
    elif op == "Pow":
        out = mx.sym.broadcast_power(sym_of(ins[0]), sym_of(ins[1]),
                                     name=name)
    elif op in ("Max", "Min"):
        fn = mx.sym.broadcast_maximum if op == "Max" else \
            mx.sym.broadcast_minimum
        out = fn(sym_of(ins[0]), sym_of(ins[1]), name=name)
    elif op == "Unsqueeze":
        axes = [int(a) for a in inits[ins[1]]] if len(ins) > 1 else \
            list(attrs.get("axes", ()))
        out = sym_of(ins[0])
        for a in sorted(axes):
            out = mx.sym.expand_dims(out, axis=int(a))
    elif op == "Squeeze":
        axes = ([int(a) for a in inits[ins[1]]] if len(ins) > 1
                else list(attrs.get("axes", ())) or None)
        out = mx.sym.squeeze(sym_of(ins[0]),
                             axis=tuple(axes) if axes else None,
                             name=name)
    elif op in ("ReduceSum", "ReduceMean", "ReduceMax", "ReduceMin"):
        fn = {"ReduceSum": "sum", "ReduceMean": "mean",
              "ReduceMax": "max", "ReduceMin": "min"}[op]
        axes = (tuple(int(a) for a in inits[ins[1]]) if len(ins) > 1
                else tuple(attrs.get("axes", ())) or None)
        out = getattr(mx.sym, fn)(
            sym_of(ins[0]), axis=axes, name=name,
            keepdims=bool(attrs.get("keepdims", 1)))
    elif op == "Slice":
        starts = [int(v) for v in inits[ins[1]]]
        ends = [int(v) for v in inits[ins[2]]]
        axes = ([int(v) for v in inits[ins[3]]] if len(ins) > 3
                else list(range(len(starts))))
        steps = ([int(v) for v in inits[ins[4]]] if len(ins) > 4
                 else [1] * len(starts))
        if any(s != 1 for s in steps):
            # strided/reversed slices: build the full slice spec over
            # max axis + 1 dims (leading axes untouched)
            nax = max(axes) + 1
            begin = [None] * nax
            end = [None] * nax
            step = [1] * nax
            for a, b, e, st in zip(axes, starts, ends, steps):
                begin[a] = None if abs(b) >= 2**31 - 1 else b
                end[a] = None if abs(e) >= 2**31 - 1 else e
                step[a] = st
            out = mx.sym.slice(sym_of(ins[0]), begin=tuple(begin),
                               end=tuple(end), step=tuple(step),
                               name=name)
        else:
            out = sym_of(ins[0])
            for a, b, e in zip(axes, starts, ends):
                out = mx.sym.slice_axis(
                    out, axis=a, begin=b,
                    end=None if e >= 2**31 - 1 else e)
    elif op == "Clip":
        lo = float(_np.asarray(inits[ins[1]]).reshape(())) \
            if len(ins) > 1 else float(attrs.get("min", -3.4e38))
        hi = float(_np.asarray(inits[ins[2]]).reshape(())) \
            if len(ins) > 2 else float(attrs.get("max", 3.4e38))
        out = mx.sym.clip(sym_of(ins[0]), a_min=lo, a_max=hi, name=name)
    elif op == "Cast":
        to = int(attrs.get("to", 1))
        # BOOL(9) round-trips as float32 0/1 — mx.where treats nonzero
        # as true, so the semantics are preserved
        dt = {1: "float32", 6: "int32", 7: "int64"}.get(to, "float32")
        out = mx.sym.Cast(sym_of(ins[0]), dtype=dt, name=name)
    elif op == "Gather":
        out = mx.sym.take(sym_of(ins[0]),
                          mx.sym.Cast(sym_of(ins[1]), dtype="float32"),
                          axis=int(attrs.get("axis", 0)), name=name)
    elif op == "Resize":
        # opset-13 form: inputs are (X, roi, scales, sizes); only the
        # scales form is supported — importing the sizes form with a
        # guessed scale would silently build a wrong graph
        if len(ins) > 3 and ins[3]:
            raise NotImplementedError(
                "ONNX Resize with a 'sizes' input is not supported; "
                "re-export with 'scales'")
        # opset-10 form is (X, scales); opset-11+ is (X, roi, scales)
        scales_name = ins[1] if len(ins) == 2 else (
            ins[2] if len(ins) > 2 else "")
        scales = inits.get(scales_name) if scales_name else None
        mode = attrs.get("mode", b"nearest")
        mode = mode.decode() if isinstance(mode, bytes) else mode
        if scales is None or len(scales) < 4:
            # guessing a scale would silently build a wrong graph
            raise NotImplementedError(
                "ONNX Resize needs a 4-element 'scales' initializer "
                "(graph-computed scales are not supported)")
        sh, sw = float(scales[2]), float(scales[3])
        if mode == "nearest":
            if sh != sw or sh != int(sh):
                raise NotImplementedError(
                    f"nearest Resize needs an integral uniform scale, "
                    f"got H={sh} W={sw}")
            out = mx.sym.UpSampling(sym_of(ins[0]), scale=int(sh),
                                    sample_type="nearest", name=name)
        else:
            out = mx.sym._contrib_BilinearResize2D(
                sym_of(ins[0]), scale_height=sh, scale_width=sw,
                name=name)
    elif op == "Where":
        out = mx.sym.where(sym_of(ins[0]), sym_of(ins[1]),
                           sym_of(ins[2]), name=name)
    elif op == "Erf":
        out = mx.sym.erf(sym_of(ins[0]), name=name)
    elif op == "Pad":
        pads = [int(v) for v in inits[ins[1]]] if len(ins) > 1 else \
            list(attrs.get("pads", ()))
        ndim = len(pads) // 2
        widths = []
        for i in range(ndim):
            widths += [pads[i], pads[ndim + i]]
        cval = 0.0
        if len(ins) > 2 and ins[2]:
            cval = float(_np.asarray(inits[ins[2]]).reshape(()))
        mode = attrs.get("mode", b"constant")
        mode = mode.decode() if isinstance(mode, bytes) else mode
        out = mx.sym.Pad(sym_of(ins[0]), mode=mode,
                         pad_width=tuple(widths), constant_value=cval,
                         name=name)
    elif op == "Constant":
        # value tensor arrives as an attribute; materialize it like an
        # initializer so downstream nodes can reference it
        raw = attrs.get("value")
        if raw is None:
            raise NotImplementedError("Constant without 'value'")
        cname, arr = _parse_tensor(raw)
        inits[outs[0]] = arr
        return sym_of(outs[0])
    else:
        raise NotImplementedError(
            f"ONNX import: no mapping for op {op!r}")
    values[outs[0]] = out
    return out


_UNARY_IMPORT = {
    "Sqrt": "sqrt", "Exp": "exp", "Log": "log", "Abs": "abs",
    "Neg": "negative", "Floor": "floor", "Ceil": "ceil", "Sign": "sign",
}
