"""Minimal protobuf wire-format encoder/decoder (no protobuf dependency).

Implements exactly the subset of proto3 wire format ONNX model files
use: varint (wire type 0), 64-bit (1), length-delimited (2), 32-bit
(5). The ONNX schema constants live in onnx_spec.py; this module knows
nothing about ONNX itself.

Encoding: build messages as lists of (field_number, wire_type, value)
where value is int (varint/fixed), bytes (length-delimited), or float
(fixed32/64). Decoding: parse bytes into {field_number: [raw values]}
— length-delimited values come back as bytes for the caller to decode
recursively.
"""
from __future__ import annotations

import struct

VARINT, FIXED64, LEN, FIXED32 = 0, 1, 2, 5


def encode_varint(v: int) -> bytes:
    if v < 0:                      # proto int64 negative: 10-byte varint
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return encode_varint((field << 3) | wire)


def encode(fields) -> bytes:
    """fields: iterable of (field_number, wire_type, value)."""
    out = bytearray()
    for field, wire, value in fields:
        out += _tag(field, wire)
        if wire == VARINT:
            out += encode_varint(int(value))
        elif wire == LEN:
            if isinstance(value, str):
                value = value.encode()
            out += encode_varint(len(value))
            out += value
        elif wire == FIXED32:
            out += struct.pack("<f", float(value))
        elif wire == FIXED64:
            out += struct.pack("<d", float(value))
        else:
            raise ValueError(f"wire type {wire}")
    return bytes(out)


def packed_varints(values) -> bytes:
    out = bytearray()
    for v in values:
        out += encode_varint(int(v))
    return bytes(out)


def decode_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 63:   # negative int64
                result -= 1 << 64
            return result, pos
        shift += 7


def decode(buf: bytes):
    """-> {field_number: [value, ...]} (bytes for LEN, int for VARINT,
    float for FIXED32/64). Packed repeated scalars arrive as one bytes
    value — use decode_packed_varints on it."""
    out = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = decode_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == VARINT:
            value, pos = decode_varint(buf, pos)
        elif wire == LEN:
            length, pos = decode_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wire == FIXED32:
            value = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        elif wire == FIXED64:
            value = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        else:
            raise ValueError(f"wire type {wire} at {pos}")
        out.setdefault(field, []).append(value)
    return out


def decode_packed_varints(buf: bytes):
    vals = []
    pos = 0
    while pos < len(buf):
        v, pos = decode_varint(buf, pos)
        vals.append(v)
    return vals
