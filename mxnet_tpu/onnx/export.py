"""Symbol+params -> ONNX ModelProto bytes.

Reference: python/mxnet/contrib/onnx/mx2onnx/export_model.py + the
per-op converters in _op_translations.py. Same translation table for
the core CNN/MLP set; serialization is the hand-rolled wire-format
encoder in _proto.py (the environment ships no onnx/protobuf package),
emitting standard ONNX (ir_version 8, opset 13) that any ONNX runtime
loads.
"""
from __future__ import annotations

import numpy as _np

from . import _proto as P

# ONNX TensorProto.DataType
TP_FLOAT, TP_INT32, TP_INT64 = 1, 6, 7
# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_INTS = 1, 2, 3, 7

_DT = {_np.dtype(_np.float32): TP_FLOAT, _np.dtype(_np.int32): TP_INT32,
       _np.dtype(_np.int64): TP_INT64}


def _attr(name, atype, value):
    fields = [(1, P.LEN, name), (20, P.VARINT, atype)]
    if atype == AT_FLOAT:
        fields.append((2, P.FIXED32, value))
    elif atype == AT_INT:
        fields.append((3, P.VARINT, value))
    elif atype == AT_STRING:
        fields.append((4, P.LEN, value))
    elif atype == AT_INTS:
        fields += [(8, P.VARINT, v) for v in value]
    return (5, P.LEN, P.encode(fields))


def _node(op_type, inputs, outputs, name, attrs=()):
    fields = [(1, P.LEN, i) for i in inputs]
    fields += [(2, P.LEN, o) for o in outputs]
    fields += [(3, P.LEN, name), (4, P.LEN, op_type)]
    fields += list(attrs)
    return (1, P.LEN, P.encode(fields))


def _tensor(name, arr):
    arr = _np.ascontiguousarray(arr)
    dt = _DT.get(arr.dtype)
    if dt is None:
        arr = arr.astype(_np.float32)
        dt = TP_FLOAT
    fields = [(1, P.VARINT, d) for d in arr.shape]
    fields += [(2, P.VARINT, dt), (8, P.LEN, name),
               (9, P.LEN, arr.tobytes())]
    return P.encode(fields)


def _value_info(name, shape, dt=TP_FLOAT):
    dims = P.encode([(1, P.VARINT, int(d)) for d in shape])
    shape_p = P.encode([(1, P.LEN, d) for d in
                        (P.encode([(1, P.VARINT, int(x))])
                         for x in shape)])
    tensor_t = P.encode([(1, P.VARINT, dt), (2, P.LEN, shape_p)])
    type_p = P.encode([(1, P.LEN, tensor_t)])
    return P.encode([(1, P.LEN, name), (2, P.LEN, type_p)])


def _ints(params, key, default):
    v = params.get(key, default)
    if v is None:
        return None
    if isinstance(v, (int, float)):
        v = (int(v),)
    return [int(x) for x in v]


class _Ctx:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.n = 0

    def name(self, base):
        self.n += 1
        return f"{base}_{self.n}"


def _convert(node, ins, out, ctx):
    """One symbol node -> ONNX node(s). ins: input value names."""
    op = node._op
    p = node._params
    nm = node._name

    if op in ("FullyConnected",):
        no_bias = bool(p.get("no_bias", False))
        # Gemm(B transposed) matches FullyConnected exactly, but needs
        # 2-D input: insert a Flatten like the reference converter
        flat = ctx.name(nm + "_flatten")
        ctx.nodes.append(_node("Flatten", [ins[0]], [flat],
                               flat, [_attr("axis", AT_INT, 1)]))
        attrs = [_attr("transB", AT_INT, 1)]
        inputs = [flat, ins[1]] + ([] if no_bias else [ins[2]])
        ctx.nodes.append(_node("Gemm", inputs, [out], nm, attrs))
    elif op == "Convolution":
        attrs = [_attr("kernel_shape", AT_INTS, _ints(p, "kernel", ()))]
        stride = _ints(p, "stride", (1, 1))
        pad = _ints(p, "pad", (0, 0))
        dil = _ints(p, "dilate", (1, 1))
        attrs += [_attr("strides", AT_INTS, stride),
                  _attr("pads", AT_INTS, pad + pad),
                  _attr("dilations", AT_INTS, dil),
                  _attr("group", AT_INT, int(p.get("num_group", 1)))]
        no_bias = bool(p.get("no_bias", False))
        inputs = ins[:2] if no_bias else ins[:3]
        ctx.nodes.append(_node("Conv", inputs, [out], nm, attrs))
    elif op == "Pooling":
        ptype = p.get("pool_type", "max")
        if p.get("global_pool", False):
            op_t = "GlobalAveragePool" if ptype == "avg" else \
                "GlobalMaxPool"
            ctx.nodes.append(_node(op_t, [ins[0]], [out], nm))
        else:
            op_t = "AveragePool" if ptype == "avg" else "MaxPool"
            stride = _ints(p, "stride", (1, 1))
            pad = _ints(p, "pad", (0, 0))
            attrs = [_attr("kernel_shape", AT_INTS,
                           _ints(p, "kernel", ())),
                     _attr("strides", AT_INTS, stride),
                     _attr("pads", AT_INTS, pad + pad)]
            ctx.nodes.append(_node(op_t, [ins[0]], [out], nm, attrs))
    elif op == "BatchNorm":
        attrs = [_attr("epsilon", AT_FLOAT, float(p.get("eps", 1e-3))),
                 _attr("momentum", AT_FLOAT,
                       float(p.get("momentum", 0.9)))]
        ctx.nodes.append(_node("BatchNormalization", ins[:5], [out], nm,
                               attrs))
    elif op == "Activation":
        act = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
               "softrelu": "Softplus"}[p.get("act_type", "relu")]
        ctx.nodes.append(_node(act, [ins[0]], [out], nm))
    elif op == "LeakyReLU":
        ctx.nodes.append(_node(
            "LeakyRelu", [ins[0]], [out], nm,
            [_attr("alpha", AT_FLOAT, float(p.get("slope", 0.25)))]))
    elif op in ("SoftmaxOutput", "softmax", "Softmax"):
        ctx.nodes.append(_node("Softmax", [ins[0]], [out], nm,
                               [_attr("axis", AT_INT,
                                      int(p.get("axis", -1)))]))
    elif op in ("Flatten", "flatten"):
        ctx.nodes.append(_node("Flatten", [ins[0]], [out], nm,
                               [_attr("axis", AT_INT, 1)]))
    elif op in ("elemwise_add", "broadcast_add", "_plus", "_add"):
        ctx.nodes.append(_node("Add", ins[:2], [out], nm))
    elif op in ("elemwise_mul", "broadcast_mul"):
        ctx.nodes.append(_node("Mul", ins[:2], [out], nm))
    elif op in ("elemwise_sub", "broadcast_sub"):
        ctx.nodes.append(_node("Sub", ins[:2], [out], nm))
    elif op in ("Concat", "concat"):
        ctx.nodes.append(_node("Concat", ins, [out], nm,
                               [_attr("axis", AT_INT,
                                      int(p.get("dim", 1)))]))
    elif op in ("Reshape", "reshape"):
        shape = [int(s) for s in p.get("shape", ())]
        shp_name = ctx.name(nm + "_shape")
        ctx.initializers.append(_tensor(
            shp_name, _np.asarray(shape, _np.int64)))
        ctx.nodes.append(_node("Reshape", [ins[0], shp_name], [out], nm))
    elif op == "Dropout":
        # inference export: Identity (reference does the same for
        # non-training exports)
        ctx.nodes.append(_node("Identity", [ins[0]], [out], nm))
    else:
        raise NotImplementedError(
            f"ONNX export: no converter for op {op!r} (reference "
            "converter table: mx2onnx/_op_translations.py)")


def export_model(sym, params, input_shapes, input_dtypes=None,
                 onnx_file_path=None, model_name="mxnet_tpu"):
    """Export a Symbol + params dict to ONNX bytes (reference:
    contrib/onnx/mx2onnx/export_model.py:33). ``input_shapes``:
    {input_name: shape}. Returns the serialized ModelProto; writes it
    to ``onnx_file_path`` when given."""
    from ..ndarray import NDArray

    params = {k: (v.asnumpy() if isinstance(v, NDArray) else
                  _np.asarray(v)) for k, v in (params or {}).items()}

    ctx = _Ctx()
    topo = sym._topo()
    # graph outputs: the symbol's outputs
    out_names = {}

    # BatchNorm fix_gamma=True (the MXNet default) ignores gamma; ONNX
    # BatchNormalization has no such switch, so fold it by exporting
    # gamma as ones (reference converter does the same)
    force_ones = set()
    for node in topo:
        if node._op == "BatchNorm" and node._params.get("fix_gamma",
                                                        True):
            if len(node._inputs) > 1:
                force_ones.add(node._inputs[1]._name)

    graph_inputs = []
    for node in topo:
        if node._is_var():
            if node._name in params:
                val = params[node._name]
                if node._name in force_ones:
                    val = _np.ones_like(val)
                ctx.initializers.append(_tensor(node._name, val))
            elif node._name in input_shapes:
                graph_inputs.append(_value_info(
                    node._name, input_shapes[node._name]))
            elif node._name.endswith("_label"):
                continue            # loss labels don't export
            else:
                raise ValueError(
                    f"input {node._name!r} needs a shape in "
                    "input_shapes or a value in params")
            out_names[id(node)] = node._name
        else:
            ins = [out_names[id(i)] for i in node._inputs
                   if id(i) in out_names]
            out = node._name + "_out"
            _convert(node, ins, out, ctx)
            out_names[id(node)] = out

    final = out_names[id(topo[-1])]
    # infer output shape for the value_info via eval_shape
    shapes = dict(input_shapes)
    try:
        _, out_shapes, _ = sym.infer_shape(**input_shapes)
        out_shape = out_shapes[0]
    except Exception:
        out_shape = ()
    graph_outputs = [_value_info(final, out_shape)]

    graph = P.encode(
        ctx.nodes
        + [(2, P.LEN, model_name)]
        + [(5, P.LEN, t) for t in ctx.initializers]
        + [(11, P.LEN, vi) for vi in graph_inputs]
        + [(12, P.LEN, vo) for vo in graph_outputs])

    opset = P.encode([(1, P.LEN, ""), (2, P.VARINT, 13)])
    model = P.encode([
        (1, P.VARINT, 8),                       # ir_version
        (2, P.LEN, "mxnet_tpu"),                # producer_name
        (7, P.LEN, graph),
        (8, P.LEN, opset),
    ])
    if onnx_file_path:
        with open(onnx_file_path, "wb") as f:
            f.write(model)
    return model
