"""Symbol+params -> ONNX ModelProto bytes.

Reference: python/mxnet/contrib/onnx/mx2onnx/export_model.py + the
per-op converters in _op_translations.py. Same translation table for
the core CNN/MLP set; serialization is the hand-rolled wire-format
encoder in _proto.py (the environment ships no onnx/protobuf package),
emitting standard ONNX (ir_version 8, opset 13) that any ONNX runtime
loads.
"""
from __future__ import annotations

import numpy as _np

from . import _proto as P

# ONNX TensorProto.DataType
TP_FLOAT, TP_INT32, TP_INT64 = 1, 6, 7
# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_INTS = 1, 2, 3, 7

_DT = {_np.dtype(_np.float32): TP_FLOAT, _np.dtype(_np.int32): TP_INT32,
       _np.dtype(_np.int64): TP_INT64}


def _attr(name, atype, value):
    fields = [(1, P.LEN, name), (20, P.VARINT, atype)]
    if atype == AT_FLOAT:
        fields.append((2, P.FIXED32, value))
    elif atype == AT_INT:
        fields.append((3, P.VARINT, value))
    elif atype == AT_STRING:
        fields.append((4, P.LEN, value))
    elif atype == AT_INTS:
        fields += [(8, P.VARINT, v) for v in value]
    return (5, P.LEN, P.encode(fields))


def _node(op_type, inputs, outputs, name, attrs=()):
    fields = [(1, P.LEN, i) for i in inputs]
    fields += [(2, P.LEN, o) for o in outputs]
    fields += [(3, P.LEN, name), (4, P.LEN, op_type)]
    fields += list(attrs)
    return (1, P.LEN, P.encode(fields))


def _tensor(name, arr):
    arr = _np.ascontiguousarray(arr)
    dt = _DT.get(arr.dtype)
    if dt is None:
        arr = arr.astype(_np.float32)
        dt = TP_FLOAT
    fields = [(1, P.VARINT, d) for d in arr.shape]
    fields += [(2, P.VARINT, dt), (8, P.LEN, name),
               (9, P.LEN, arr.tobytes())]
    return P.encode(fields)


def _value_info(name, shape, dt=TP_FLOAT):
    dims = P.encode([(1, P.VARINT, int(d)) for d in shape])
    shape_p = P.encode([(1, P.LEN, d) for d in
                        (P.encode([(1, P.VARINT, int(x))])
                         for x in shape)])
    tensor_t = P.encode([(1, P.VARINT, dt), (2, P.LEN, shape_p)])
    type_p = P.encode([(1, P.LEN, tensor_t)])
    return P.encode([(1, P.LEN, name), (2, P.LEN, type_p)])


def _ints(params, key, default):
    v = params.get(key, default)
    if v is None:
        return None
    if isinstance(v, (int, float)):
        v = (int(v),)
    return [int(x) for x in v]


class _Ctx:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.n = 0
        self.node_shapes = {}    # id(sym node) -> inferred out shape

    def name(self, base):
        self.n += 1
        return f"{base}_{self.n}"

    def out_shape(self, node):
        s = self.node_shapes.get(id(node))
        if isinstance(s, list):
            s = s[0]
        return s


# simple elementwise unaries with a 1:1 ONNX node
_EW_UNARY = {
    "sqrt": "Sqrt", "exp": "Exp", "log": "Log", "tanh": "Tanh",
    "sigmoid": "Sigmoid", "erf": "Erf", "relu": "Relu", "abs": "Abs",
    "negative": "Neg", "floor": "Floor", "ceil": "Ceil", "sign": "Sign",
}

# mx scalar op -> (onnx op, operands swapped?)
_SCALAR_BIN = {
    "_plus_scalar": ("Add", False), "_minus_scalar": ("Sub", False),
    "_rminus_scalar": ("Sub", True), "_mul_scalar": ("Mul", False),
    "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True),
    "_power_scalar": ("Pow", False), "_rpower_scalar": ("Pow", True),
    "_maximum_scalar": ("Max", False), "_minimum_scalar": ("Min", False),
}


def _convert(node, ins, out, ctx):
    """One symbol node -> ONNX node(s). ins: input value names."""
    op = node._op
    p = node._params
    nm = node._name

    if op in ("FullyConnected",):
        no_bias = bool(p.get("no_bias", False))
        if p.get("flatten", True):
            # Gemm(B transposed) matches FullyConnected exactly, but
            # needs 2-D input: insert a Flatten like the reference
            flat = ctx.name(nm + "_flatten")
            ctx.nodes.append(_node("Flatten", [ins[0]], [flat],
                                   flat, [_attr("axis", AT_INT, 1)]))
            attrs = [_attr("transB", AT_INT, 1)]
            inputs = [flat, ins[1]] + ([] if no_bias else [ins[2]])
            ctx.nodes.append(_node("Gemm", inputs, [out], nm, attrs))
        else:
            # per-token projection: x @ W^T (+ b) on the last axis
            wt = ctx.name(nm + "_wt")
            ctx.nodes.append(_node("Transpose", [ins[1]], [wt], wt, [
                _attr("perm", AT_INTS, [1, 0])]))
            if no_bias:
                ctx.nodes.append(_node("MatMul", [ins[0], wt], [out],
                                       nm))
            else:
                mm = ctx.name(nm + "_mm")
                ctx.nodes.append(_node("MatMul", [ins[0], wt], [mm],
                                       mm))
                ctx.nodes.append(_node("Add", [mm, ins[2]], [out], nm))
    elif op == "Convolution":
        attrs = [_attr("kernel_shape", AT_INTS, _ints(p, "kernel", ()))]
        stride = _ints(p, "stride", (1, 1))
        pad = _ints(p, "pad", (0, 0))
        dil = _ints(p, "dilate", (1, 1))
        attrs += [_attr("strides", AT_INTS, stride),
                  _attr("pads", AT_INTS, pad + pad),
                  _attr("dilations", AT_INTS, dil),
                  _attr("group", AT_INT, int(p.get("num_group", 1)))]
        no_bias = bool(p.get("no_bias", False))
        inputs = ins[:2] if no_bias else ins[:3]
        ctx.nodes.append(_node("Conv", inputs, [out], nm, attrs))
    elif op == "Pooling":
        ptype = p.get("pool_type", "max")
        if p.get("global_pool", False):
            op_t = "GlobalAveragePool" if ptype == "avg" else \
                "GlobalMaxPool"
            ctx.nodes.append(_node(op_t, [ins[0]], [out], nm))
        else:
            op_t = "AveragePool" if ptype == "avg" else "MaxPool"
            stride = _ints(p, "stride", (1, 1))
            pad = _ints(p, "pad", (0, 0))
            attrs = [_attr("kernel_shape", AT_INTS,
                           _ints(p, "kernel", ())),
                     _attr("strides", AT_INTS, stride),
                     _attr("pads", AT_INTS, pad + pad)]
            ctx.nodes.append(_node(op_t, [ins[0]], [out], nm, attrs))
    elif op == "BatchNorm":
        attrs = [_attr("epsilon", AT_FLOAT, float(p.get("eps", 1e-3))),
                 _attr("momentum", AT_FLOAT,
                       float(p.get("momentum", 0.9)))]
        ctx.nodes.append(_node("BatchNormalization", ins[:5], [out], nm,
                               attrs))
    elif op == "Activation":
        act = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
               "softrelu": "Softplus"}[p.get("act_type", "relu")]
        ctx.nodes.append(_node(act, [ins[0]], [out], nm))
    elif op == "LeakyReLU":
        ctx.nodes.append(_node(
            "LeakyRelu", [ins[0]], [out], nm,
            [_attr("alpha", AT_FLOAT, float(p.get("slope", 0.25)))]))
    elif op in ("SoftmaxOutput", "softmax", "Softmax"):
        ctx.nodes.append(_node("Softmax", [ins[0]], [out], nm,
                               [_attr("axis", AT_INT,
                                      int(p.get("axis", -1)))]))
    elif op in ("Flatten", "flatten"):
        ctx.nodes.append(_node("Flatten", [ins[0]], [out], nm,
                               [_attr("axis", AT_INT, 1)]))
    elif op in ("elemwise_add", "broadcast_add", "_plus", "_add"):
        ctx.nodes.append(_node("Add", ins[:2], [out], nm))
    elif op in ("elemwise_mul", "broadcast_mul"):
        ctx.nodes.append(_node("Mul", ins[:2], [out], nm))
    elif op in ("elemwise_sub", "broadcast_sub"):
        ctx.nodes.append(_node("Sub", ins[:2], [out], nm))
    elif op in ("Concat", "concat"):
        ctx.nodes.append(_node("Concat", ins, [out], nm,
                               [_attr("axis", AT_INT,
                                      int(p.get("dim", 1)))]))
    elif op in ("Reshape", "reshape"):
        # mxnet reshape specials (0-cursor, -1..-4) are not
        # ONNX-expressible: 0 copies by a moving cursor here but by
        # output index in ONNX, and -3/-4 merge/split dims. Export the
        # concretely inferred output shape instead (shapes are known —
        # export_model fixes the input shapes).
        shape = ctx.out_shape(node)
        if shape is None:
            raw = [int(s) for s in p.get("shape", ())]
            # a single -1 among positive dims means the same thing in
            # ONNX; 0 (cursor copy here, positional copy there) and
            # -2/-3/-4 do not — refusing beats exporting a silently
            # wrong graph
            if any(s == 0 or s < -1 for s in raw) or raw.count(-1) > 1:
                raise NotImplementedError(
                    "ONNX export: Reshape with special dims "
                    f"{tuple(raw)} needs inferable shapes (pass "
                    "concrete input_shapes)")
            shape = raw
        shp_name = ctx.name(nm + "_shape")
        ctx.initializers.append(_tensor(
            shp_name, _np.asarray(list(shape), _np.int64)))
        ctx.nodes.append(_node("Reshape", [ins[0], shp_name], [out], nm))
    elif op == "Dropout":
        # inference export: Identity (reference does the same for
        # non-training exports)
        ctx.nodes.append(_node("Identity", [ins[0]], [out], nm))
    elif op == "Deconvolution":
        stride = _ints(p, "stride", (1, 1))
        pad = _ints(p, "pad", (0, 0))
        attrs = [_attr("kernel_shape", AT_INTS, _ints(p, "kernel", ())),
                 _attr("strides", AT_INTS, stride),
                 _attr("pads", AT_INTS, pad + pad),
                 _attr("group", AT_INT, int(p.get("num_group", 1)))]
        adj = _ints(p, "adj", None)
        if adj:
            attrs.append(_attr("output_padding", AT_INTS, adj))
        no_bias = bool(p.get("no_bias", False))
        ctx.nodes.append(_node("ConvTranspose",
                               ins[:2] if no_bias else ins[:3], [out],
                               nm, attrs))
    elif op in ("transpose", "Transpose"):
        axes = _ints(p, "axes", None)
        attrs = [_attr("perm", AT_INTS, axes)] if axes else []
        ctx.nodes.append(_node("Transpose", [ins[0]], [out], nm, attrs))
    elif op in ("dot", "batch_dot", "_linalg_gemm2"):
        a, b = ins[0], ins[1]

        def _swap_last2(value, inp_node, tag):
            shape = ctx.out_shape(inp_node)
            rank = len(shape) if shape else (3 if op != "dot" else 2)
            perm = list(range(rank - 2)) + [rank - 1, rank - 2]
            t = ctx.name(nm + tag)
            ctx.nodes.append(_node("Transpose", [value], [t], t, [
                _attr("perm", AT_INTS, perm)]))
            return t

        if p.get("transpose_a", False):
            a = _swap_last2(a, node._inputs[0], "_ta")
        if p.get("transpose_b", False):
            b = _swap_last2(b, node._inputs[1], "_tb")
        alpha = float(p.get("alpha", 1.0))
        if alpha != 1.0:
            mm = ctx.name(nm + "_mm")
            ctx.nodes.append(_node("MatMul", [a, b], [mm], mm))
            ac = ctx.name(nm + "_alpha")
            ctx.initializers.append(_tensor(
                ac, _np.asarray(alpha, _np.float32)))
            ctx.nodes.append(_node("Mul", [mm, ac], [out], nm))
        else:
            ctx.nodes.append(_node("MatMul", [a, b], [out], nm))
    elif op == "LayerNorm":
        axis = int(p.get("axis", -1))
        shape = ctx.out_shape(node)
        # ONNX LayerNormalization normalizes over [axis, rank); mxnet
        # over the single `axis` — they only coincide for the last axis
        if axis != -1 and not (shape and axis == len(shape) - 1):
            raise NotImplementedError(
                "ONNX export: LayerNorm only with axis=-1 (ONNX "
                "normalizes over a trailing RANGE of axes)")
        attrs = [_attr("epsilon", AT_FLOAT, float(p.get("eps", 1e-5))),
                 _attr("axis", AT_INT, -1)]
        ctx.nodes.append(_node("LayerNormalization", ins[:3], [out], nm,
                               attrs))
    elif op == "InstanceNorm":
        attrs = [_attr("epsilon", AT_FLOAT, float(p.get("eps", 1e-3)))]
        ctx.nodes.append(_node("InstanceNormalization", ins[:3], [out],
                               nm, attrs))
    elif op in _EW_UNARY:
        ctx.nodes.append(_node(_EW_UNARY[op], [ins[0]], [out], nm))
    elif op == "square":
        ctx.nodes.append(_node("Mul", [ins[0], ins[0]], [out], nm))
    elif op in ("elemwise_div", "broadcast_div"):
        ctx.nodes.append(_node("Div", ins[:2], [out], nm))
    elif op in ("broadcast_power",):
        ctx.nodes.append(_node("Pow", ins[:2], [out], nm))
    elif op in _SCALAR_BIN:
        onnx_op, swap = _SCALAR_BIN[op]
        sc = ctx.name(nm + "_const")
        ctx.initializers.append(_tensor(
            sc, _np.asarray(float(p.get("scalar", 0.0)), _np.float32)))
        pair = [sc, ins[0]] if swap else [ins[0], sc]
        ctx.nodes.append(_node(onnx_op, pair, [out], nm))
    elif op in ("expand_dims",):
        ax = ctx.name(nm + "_axes")
        ctx.initializers.append(_tensor(
            ax, _np.asarray([int(p.get("axis", 0))], _np.int64)))
        ctx.nodes.append(_node("Unsqueeze", [ins[0], ax], [out], nm))
    elif op in ("squeeze",):
        axis = _ints(p, "axis", None)
        inputs = [ins[0]]
        if axis is not None:
            ax = ctx.name(nm + "_axes")
            ctx.initializers.append(_tensor(
                ax, _np.asarray(axis, _np.int64)))
            inputs.append(ax)
        ctx.nodes.append(_node("Squeeze", inputs, [out], nm))
    elif op in ("sum", "mean", "max", "min"):
        onnx_op = {"sum": "ReduceSum", "mean": "ReduceMean",
                   "max": "ReduceMax", "min": "ReduceMin"}[op]
        axis = _ints(p, "axis", None)
        keep = _attr("keepdims", AT_INT,
                     1 if p.get("keepdims", False) else 0)
        if op == "sum" and axis is not None:
            ax = ctx.name(nm + "_axes")
            ctx.initializers.append(_tensor(
                ax, _np.asarray(axis, _np.int64)))
            ctx.nodes.append(_node(onnx_op, [ins[0], ax], [out], nm,
                                   [keep]))
        else:
            attrs = [keep]
            if axis is not None:
                attrs.append(_attr("axes", AT_INTS, axis))
            ctx.nodes.append(_node(onnx_op, [ins[0]], [out], nm, attrs))
    elif op in ("slice", "slice_axis"):
        if op == "slice_axis":
            axes = [int(p["axis"])]
            begin = [int(p["begin"])]
            end = [int(p["end"]) if p.get("end") is not None else 2**31]
            step = [1]
        else:
            step = [1 if s is None else int(s)
                    for s in (p.get("step") or
                              [1] * len(p.get("begin", ())))]
            # open-ended (None) begin/end mean "the far edge in the
            # step's direction", so the sentinels must follow the sign:
            # ONNX clamps +INT_MAX to dim-1 and -INT_MIN to -1, which
            # would make a reversed open slice start at 0 / end empty
            begin = [(2**31 if step[i] < 0 else 0) if b is None
                     else int(b)
                     for i, b in enumerate(p.get("begin", ()))]
            end = [(-2**31 if step[i] < 0 else 2**31) if e is None
                   else int(e)
                   for i, e in enumerate(p.get("end", ()))]
            axes = list(range(len(begin)))
        names = []
        for tag, vals in (("_starts", begin), ("_ends", end),
                          ("_axes", axes), ("_steps", step)):
            cn = ctx.name(nm + tag)
            ctx.initializers.append(_tensor(
                cn, _np.asarray(vals, _np.int64)))
            names.append(cn)
        ctx.nodes.append(_node("Slice", [ins[0]] + names, [out], nm))
    elif op in ("clip",):
        lo = ctx.name(nm + "_min")
        hi = ctx.name(nm + "_max")
        ctx.initializers.append(_tensor(
            lo, _np.asarray(float(p.get("a_min", 0.0)), _np.float32)))
        ctx.initializers.append(_tensor(
            hi, _np.asarray(float(p.get("a_max", 0.0)), _np.float32)))
        ctx.nodes.append(_node("Clip", [ins[0], lo, hi], [out], nm))
    elif op == "Embedding":
        idx = ctx.name(nm + "_idx")
        ctx.nodes.append(_node("Cast", [ins[0]], [idx], idx,
                               [_attr("to", AT_INT, TP_INT64)]))
        ctx.nodes.append(_node("Gather", [ins[1], idx], [out], nm))
    elif op in ("UpSampling", "_contrib_BilinearResize2D"):
        mode = "nearest" if p.get("sample_type", "nearest") == "nearest" \
            and op == "UpSampling" else "linear"
        roi = ctx.name(nm + "_roi")
        ctx.initializers.append(_tensor(
            roi, _np.asarray([], _np.float32)))
        sc = ctx.name(nm + "_scales")
        if op == "UpSampling":
            sh = sw = float(p.get("scale", 2))
        else:
            # BilinearResize2D takes height/width or scale_height/_width;
            # derive the true scales from the inferred in/out shapes
            in_shape = ctx.out_shape(node._inputs[0])
            out_shape = ctx.out_shape(node)
            if in_shape and out_shape:
                sh = out_shape[2] / in_shape[2]
                sw = out_shape[3] / in_shape[3]
            elif p.get("scale_height") is not None:
                sh = float(p["scale_height"])
                sw = float(p.get("scale_width", sh))
            else:
                raise NotImplementedError(
                    "ONNX export: BilinearResize2D needs inferable "
                    "shapes or scale_height/scale_width")
        ctx.initializers.append(_tensor(
            sc, _np.asarray([1.0, 1.0, sh, sw], _np.float32)))
        ctx.nodes.append(_node(
            "Resize", [ins[0], roi, sc], [out], nm,
            [_attr("mode", AT_STRING, mode)]))
    elif op in ("Pad", "pad"):
        widths = [int(w) for w in p.get("pad_width", ())]
        ndim = len(widths) // 2
        # mxnet interleaves (before,after) per axis; ONNX wants all
        # befores then all afters
        pads = ([widths[2 * i] for i in range(ndim)]
                + [widths[2 * i + 1] for i in range(ndim)])
        pn = ctx.name(nm + "_pads")
        ctx.initializers.append(_tensor(
            pn, _np.asarray(pads, _np.int64)))
        cn = ctx.name(nm + "_cval")
        ctx.initializers.append(_tensor(
            cn, _np.asarray(float(p.get("constant_value", 0)),
                            _np.float32)))
        mode = p.get("mode", "constant")
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect"}.get(mode, "constant")
        ctx.nodes.append(_node("Pad", [ins[0], pn, cn], [out], nm,
                               [_attr("mode", AT_STRING, mode)]))
    elif op == "where":
        b = ctx.name(nm + "_cond")
        ctx.nodes.append(_node("Cast", [ins[0]], [b], b,
                               [_attr("to", AT_INT, 9)]))  # BOOL
        ctx.nodes.append(_node("Where", [b, ins[1], ins[2]], [out], nm))
    else:
        raise NotImplementedError(
            f"ONNX export: no converter for op {op!r} (reference "
            "converter table: mx2onnx/_op_translations.py)")


def export_model(sym, params, input_shapes, input_dtypes=None,
                 onnx_file_path=None, model_name="mxnet_tpu"):
    """Export a Symbol + params dict to ONNX bytes (reference:
    contrib/onnx/mx2onnx/export_model.py:33). ``input_shapes``:
    {input_name: shape}. Returns the serialized ModelProto; writes it
    to ``onnx_file_path`` when given."""
    from ..ndarray import NDArray

    params = {k: (v.asnumpy() if isinstance(v, NDArray) else
                  _np.asarray(v)) for k, v in (params or {}).items()}

    ctx = _Ctx()
    topo = sym._topo()
    # per-node output shapes: lets converters resolve shape-dependent
    # attributes (mxnet Reshape specials) to concrete dims
    known = dict(input_shapes)
    known.update({k: tuple(v.shape) for k, v in params.items()})
    try:
        _, _, ctx.node_shapes = sym._solve_shapes(known, partial=True)
    except Exception:
        pass
    # graph outputs: the symbol's outputs
    out_names = {}

    # BatchNorm fix_gamma=True (the MXNet default) ignores gamma; ONNX
    # BatchNormalization has no such switch, so fold it by exporting
    # gamma as ones (reference converter does the same)
    force_ones = set()
    for node in topo:
        if node._op == "BatchNorm" and node._params.get("fix_gamma",
                                                        True):
            if len(node._inputs) > 1:
                force_ones.add(node._inputs[1]._name)

    graph_inputs = []
    for node in topo:
        if node._is_var():
            if node._name in params:
                val = params[node._name]
                if node._name in force_ones:
                    val = _np.ones_like(val)
                ctx.initializers.append(_tensor(node._name, val))
            elif node._name in input_shapes:
                graph_inputs.append(_value_info(
                    node._name, input_shapes[node._name]))
            elif node._name.endswith("_label"):
                continue            # loss labels don't export
            else:
                raise ValueError(
                    f"input {node._name!r} needs a shape in "
                    "input_shapes or a value in params")
            out_names[id(node)] = node._name
        else:
            ins = [out_names[id(i)] for i in node._inputs
                   if id(i) in out_names]
            out = node._name + "_out"
            _convert(node, ins, out, ctx)
            out_names[id(node)] = out

    final = out_names[id(topo[-1])]
    # infer output shape for the value_info via eval_shape
    shapes = dict(input_shapes)
    try:
        _, out_shapes, _ = sym.infer_shape(**input_shapes)
        out_shape = out_shapes[0]
    except Exception:
        out_shape = ()
    graph_outputs = [_value_info(final, out_shape)]

    graph = P.encode(
        ctx.nodes
        + [(2, P.LEN, model_name)]
        + [(5, P.LEN, t) for t in ctx.initializers]
        + [(11, P.LEN, vi) for vi in graph_inputs]
        + [(12, P.LEN, vo) for vo in graph_outputs])

    # opset 17: lowest with LayerNormalization; everything else emitted
    # here is stable since 13
    opset = P.encode([(1, P.LEN, ""), (2, P.VARINT, 17)])
    model = P.encode([
        (1, P.VARINT, 8),                       # ir_version
        (2, P.LEN, "mxnet_tpu"),                # producer_name
        (7, P.LEN, graph),
        (8, P.LEN, opset),
    ])
    if onnx_file_path:
        with open(onnx_file_path, "wb") as f:
            f.write(model)
    return model
