"""Split a gluon network into pipeline stages for HeteroPipeline.

Real models (ResNet, BERT) change activation shape between stages, so
each stage becomes its own sub-network with its own param pytree; the
packed-register schedule in ``pipeline.HeteroPipeline`` runs them under
one jitted scan. (The reference has no pipeline parallelism to cite —
SURVEY.md §2.3; this is TPU-native capability.)

BatchNorm note: stage fns run with ``training=True`` (batch statistics)
but drop running-stat updates — the pipeline schedule is stateless. The
sequential oracle used in tests does the same, so gradients are exactly
comparable; fold running stats offline if inference-time stats matter.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .functional import extract_params, functional_call

__all__ = ["gluon_pipeline_stages"]


def gluon_pipeline_stages(net, boundaries, sample_shape,
                          dtype=jnp.float32):
    """Partition ``net`` (a features+output gluon model) into pipeline
    stages split at ``boundaries`` (indices into ``net.features``).

    Returns ``(stage_fns, stage_params, act_shapes)`` ready to hand to
    :class:`mxnet_tpu.parallel.HeteroPipeline`:
      - ``stage_fns[i](params, x)`` applies stage i's sub-network
        functionally (training-mode BN, see module docstring);
      - ``stage_params[i]`` is the stage's param dict (disjoint across
        stages, names preserved from the net);
      - ``act_shapes`` are the per-boundary activation shapes (without
        the microbatch dim), inferred with ``jax.eval_shape`` from
        ``sample_shape`` (a full input shape INCLUDING the microbatch
        dim, e.g. ``(mb, 3, 32, 32)``).

    The net must already be initialized (shapes known).
    """
    from ..gluon import nn

    children = list(net.features)
    idx = [0] + sorted(boundaries) + [len(children)]
    if any(a >= b for a, b in zip(idx[:-1], idx[1:])):
        raise ValueError(f"boundaries {boundaries} must be strictly "
                         f"increasing within (0, {len(children)})")
    groups = []
    for a, b in zip(idx[:-1], idx[1:]):
        seq = nn.HybridSequential(prefix=f"pipe_stage{len(groups)}_")
        seq.add(*children[a:b])  # shares the blocks; names unchanged
        groups.append(seq)
    if getattr(net, "output", None) is not None:
        groups[-1].add(net.output)

    stage_params = [extract_params(g) for g in groups]

    def make_fn(group):
        def fn(params, x):
            return functional_call(group, params, x, training=True)[0]
        return fn

    stage_fns = [make_fn(g) for g in groups]

    act_shapes = []
    spec = jax.ShapeDtypeStruct(tuple(sample_shape), dtype)
    act_shapes.append(tuple(spec.shape[1:]))
    for fn, p in zip(stage_fns, stage_params):
        spec = jax.eval_shape(fn, p, spec)
        act_shapes.append(tuple(spec.shape[1:]))
    return stage_fns, stage_params, act_shapes
