"""One gated jax-version compat surface for the SPMD stack.

jax moved ``shard_map`` from ``jax.experimental.shard_map`` into the
top-level namespace in 0.6 and introduced the vma ("varying manual
axes") type system (``lax.pvary``) at the same time. Every module that
lowers onto ``shard_map`` — ``parallel.pipeline``,
``parallel.ring_attention``, tests that build ad-hoc collectives — must
resolve the same three symbols the same way, so they live here instead
of per-module try/except blocks (the PR 1 shim covered the library
modules but not direct ``from jax import shard_map`` imports; this
module is the one import path that works on both sides):

- :func:`shard_map` — the per-device-rank mapping transform itself.
- :func:`pvary` — vma varying-ness annotation; identity on pre-0.6 jax,
  which has no vma types and needs no annotation.
- :data:`SHARD_MAP_KWARGS` — extra kwargs for ``shard_map``: pre-vma jax
  runs a ``check_rep`` pass that rejects legitimate per-rank
  switch/accumulate patterns the pvary annotations would legitimize, so
  it is disabled there (``{"check_rep": False}``) and empty on 0.6+.
"""
from __future__ import annotations

from jax import lax

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:  # pre-0.6 jax keeps it in the experimental namespace
    from jax.experimental.shard_map import shard_map  # noqa: F401

#: vma varying-ness annotation: identity on pre-0.6 jax.
pvary = getattr(lax, "pvary", lambda x, axes: x)

#: extra shard_map kwargs: pre-vma jax's check_rep pass rejects per-rank
#: switch/accum patterns the pvary annotations would legitimize.
SHARD_MAP_KWARGS = {} if hasattr(lax, "pvary") else {"check_rep": False}

__all__ = ["shard_map", "pvary", "SHARD_MAP_KWARGS"]
