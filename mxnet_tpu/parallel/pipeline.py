"""Pipeline parallelism: microbatched GPipe stage loop, trainable.

The reference has no pipeline parallelism (SURVEY.md §2.3); provided as
a TPU-native capability. GPipe forward schedule expressed inside
``shard_map`` over the 'pp' mesh axis: each rank holds one stage's
params and an activation register; every tick it applies its stage and
passes the activation to the next rank via ``ppermute`` — XLA overlaps
the ICI hop with the next tick's compute.

The tick loop is a ``lax.scan``, so the whole schedule is REVERSE-MODE
DIFFERENTIABLE: ``jax.grad`` of a loss on the pipe's outputs yields the
GPipe backward schedule automatically (the scan transpose runs the ticks
in reverse and the ``ppermute`` transpose sends cotangents across the
inverse permutation — backward activations flow last-stage -> first).
``pipeline_value_and_grad`` packages that into a training step.

``pipeline_stage_loop`` constrains all stages to map activations of one
shape to the same shape. ``hetero_pipeline`` lifts that for real models
(ResNet/BERT stages change activation shapes): per-stage param pytrees
are raveled, zero-padded to the widest stage, and stacked into one
(n_stages, P_max) array sharded along 'pp' — each rank holds exactly its
own stage's weights. Activations travel between ranks as a padded
(mb, A_max) register; each rank applies a stage-indexed ``lax.switch``
whose branch statically unpacks its own input shape/params, runs its
sub-network, and repacks. Padding makes every ICI hop max-activation
sized — the SPMD price of shape-heterogeneous stages — but keeps the
whole schedule one jitted scan, still reverse-mode differentiable.
"""
from __future__ import annotations

import math as _math

import jax
import jax.flatten_util
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import shard_map, pvary as _pvary, \
    SHARD_MAP_KWARGS as _SM_KW

__all__ = ["pipeline_stage_loop", "pipeline_value_and_grad",
           "hetero_pipeline", "HeteroPipeline"]


def pipeline_stage_loop(stage_fn, n_microbatches: int, mesh: Mesh,
                        axis_name: str = "pp"):
    """Build ``f(stage_params, microbatches) -> outputs``.

    - ``stage_params``: pytree whose leaves carry a leading pp-sharded
      stage axis (leaf shape (n_stages, ...)); rank i uses slice i.
    - ``microbatches``: (n_microbatches, mb, ...) replicated input; rank 0
      feeds them into the pipe in order.
    - returns (n_microbatches, mb, ...) — the last stage's outputs,
      broadcast to all ranks.
    """
    n_stages = mesh.shape[axis_name]
    ticks = n_stages + n_microbatches - 1

    def local(params, mbs):
        # shard_map hands each rank its stage slice with leading dim 1
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        rank = lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        reg0 = _pvary(jnp.zeros_like(mbs[0]), (axis_name,))
        out0 = _pvary(jnp.zeros_like(mbs), (axis_name,))

        def tick(carry, t):
            reg, out = carry
            feed_idx = jnp.clip(t, 0, n_microbatches - 1)
            inp = jnp.where(rank == 0, mbs[feed_idx], reg)
            y = stage_fn(params, inp)
            # rank n-1 finishes microbatch t-(n_stages-1) at tick t
            done_idx = t - (n_stages - 1)
            valid = (done_idx >= 0) & (rank == n_stages - 1)
            slot = jnp.clip(done_idx, 0, n_microbatches - 1)
            out = out.at[slot].set(jnp.where(valid, y, out[slot]))
            reg = lax.ppermute(y, axis_name, perm)
            return (reg, out), None

        (reg, out), _ = lax.scan(tick, (reg0, out0),
                                 jnp.arange(ticks))
        # broadcast last rank's outputs to everyone
        out = jnp.where(rank == n_stages - 1, out, jnp.zeros_like(out))
        return lax.psum(out, axis_name)

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis_name), P()),
                     out_specs=P(), **_SM_KW)


def pipeline_value_and_grad(stage_fn, loss_fn, n_microbatches: int,
                            mesh: Mesh, axis_name: str = "pp"):
    """Build a GPipe TRAINING step core:
    ``f(stage_params, microbatches, labels) -> (loss, grads)``.

    - ``loss_fn(outputs, labels) -> per-microbatch scalar`` is applied to
      each finished microbatch (labels shaped (n_microbatches, mb, ...));
      the reported loss is their mean.
    - ``grads`` has the same pp-sharded (n_stages, ...) structure as
      ``stage_params`` — each rank ends up holding exactly its own
      stage's gradients, computed by the reverse pipeline schedule that
      jax.grad derives from the forward scan.

    Wrap the result in ``jax.jit`` together with an optimizer update for
    a full pipeline-parallel train step (see tests/test_parallel.py and
    __graft_entry__.dryrun_multichip).
    """
    pipe = pipeline_stage_loop(stage_fn, n_microbatches, mesh,
                               axis_name=axis_name)

    def loss_of(params, mbs, labels):
        outs = pipe(params, mbs)
        per_mb = jax.vmap(loss_fn)(outs, labels)
        return per_mb.mean()

    def step(params, mbs, labels):
        return jax.value_and_grad(loss_of)(params, mbs, labels)

    return step


# --------------------------------------------------------------------------
# Heterogeneous stages (real models: activation shapes change per stage)
# --------------------------------------------------------------------------

class HeteroPipeline:
    """GPipe over stages with DIFFERENT param pytrees and activation
    shapes.

    Parameters
    ----------
    stage_fns : list of ``fn(params_pytree, x) -> y`` — stage i maps an
        activation of ``act_shapes[i]`` to ``act_shapes[i+1]`` (shapes
        exclude the microbatch dim).
    stage_params : list of per-stage param pytrees (used for layout
        metadata AND as the initial packed values).
    act_shapes : list of ``n_stages + 1`` activation shapes, microbatch
        dim excluded; ``act_shapes[0]`` is the pipe input,
        ``act_shapes[-1]`` the output.
    microbatch, n_microbatches, mesh, axis_name: schedule config.

    Attributes/methods
    ------------------
    ``packed``            initial (n_stages, P_max) param array — place it
                          with ``P(axis_name)`` sharding.
    ``unpack_params(a)``  packed array -> list of per-stage pytrees
                          (host-side inspection / checkpointing).
    ``pack_params(ps)``   inverse of ``unpack_params``.
    ``__call__(packed, mbs)`` forward: ``mbs`` is
                          (n_mb, microbatch) + act_shapes[0].
    ``value_and_grad(loss_fn)`` -> ``step(packed, mbs, labels) ->
                          (loss, packed_grads)`` where ``packed_grads``
                          matches ``packed`` (optimizer can update the
                          packed representation directly; unpack only to
                          inspect).
    """

    def __init__(self, stage_fns, stage_params, act_shapes, microbatch,
                 n_microbatches, mesh: Mesh, axis_name: str = "pp",
                 register_dtype=jnp.float32):
        n_stages = mesh.shape[axis_name]
        if len(stage_fns) != n_stages:
            raise ValueError(f"{len(stage_fns)} stage fns for a "
                             f"{n_stages}-way {axis_name!r} mesh axis")
        if len(act_shapes) != n_stages + 1:
            raise ValueError("need n_stages+1 activation shapes")
        self.mesh, self.axis_name = mesh, axis_name
        self.n_stages, self.n_microbatches = n_stages, n_microbatches
        self.microbatch = microbatch
        self.act_shapes = [tuple(s) for s in act_shapes]
        self._rdtype = register_dtype

        flat = [jax.flatten_util.ravel_pytree(p) for p in stage_params]
        self._sizes = [v.size for v, _ in flat]
        self._unravels = [u for _, u in flat]
        self._pmax = max(self._sizes)
        self.packed = jnp.stack([
            jnp.pad(v.astype(register_dtype), (0, self._pmax - v.size))
            for v, _ in flat])
        self._amax = max(_math.prod(s) if s else 1
                         for s in self.act_shapes)
        self._stage_fns = list(stage_fns)

    # ---- packing helpers -------------------------------------------------
    def pack_params(self, stage_params):
        vs = [jax.flatten_util.ravel_pytree(p)[0] for p in stage_params]
        return jnp.stack([
            jnp.pad(v.astype(self._rdtype), (0, self._pmax - v.size))
            for v in vs])

    def unpack_params(self, packed):
        return [self._unravels[i](packed[i, :self._sizes[i]])
                for i in range(self.n_stages)]

    def _pack_act(self, y):
        flat = y.reshape(y.shape[0], -1).astype(self._rdtype)
        return jnp.pad(flat, ((0, 0), (0, self._amax - flat.shape[1])))

    def _unpack_act(self, reg, stage):
        shape = self.act_shapes[stage]
        n = _math.prod(shape) if shape else 1
        return reg[:, :n].reshape((reg.shape[0],) + shape)

    def _branches(self):
        def make(i):
            def branch(pvec, reg):
                params = self._unravels[i](pvec[:self._sizes[i]])
                x = self._unpack_act(reg, i)
                y = self._stage_fns[i](params, x)
                return self._pack_act(y)
            return branch
        return [make(i) for i in range(self.n_stages)]

    # ---- schedule --------------------------------------------------------
    def __call__(self, packed, mbs):
        """Forward: (n_mb, microbatch) + act_shapes[0] -> outputs of
        shape (n_mb, microbatch) + act_shapes[-1], replicated."""
        n_stages, n_mb = self.n_stages, self.n_microbatches
        axis = self.axis_name
        ticks = n_stages + n_mb - 1
        branches = self._branches()

        def local(packed, mbs):
            pvec = packed[0]           # this rank's stage slice
            rank = lax.axis_index(axis)
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            mb_regs = jax.vmap(self._pack_act)(mbs)
            reg0 = _pvary(jnp.zeros_like(mb_regs[0]), (axis,))
            out0 = _pvary(jnp.zeros_like(mb_regs), (axis,))

            def tick(carry, t):
                reg, out = carry
                feed_idx = jnp.clip(t, 0, n_mb - 1)
                inp = jnp.where(rank == 0, mb_regs[feed_idx], reg)
                y = lax.switch(rank, branches, pvec, inp)
                done_idx = t - (n_stages - 1)
                # upper bound matters: with ticks > n_mb + n_stages - 1
                # the clip would let duplicate recomputations overwrite
                # the last slot (same values forward, but the backward
                # cotangent then rides the longer duplicate chain)
                valid = ((done_idx >= 0) & (done_idx <= n_mb - 1) &
                         (rank == n_stages - 1))
                slot = jnp.clip(done_idx, 0, n_mb - 1)
                out = out.at[slot].set(jnp.where(valid, y, out[slot]))
                reg = lax.ppermute(y, axis, perm)
                return (reg, out), None

            (_, out), _ = lax.scan(tick, (reg0, out0), jnp.arange(ticks))
            out = jnp.where(rank == n_stages - 1, out,
                            jnp.zeros_like(out))
            return lax.psum(out, axis)

        out = shard_map(local, mesh=self.mesh,
                        in_specs=(P(self.axis_name), P()),
                        out_specs=P(), **_SM_KW)(packed, mbs)
        return jax.vmap(lambda r: self._unpack_act(r, self.n_stages))(out)

    def value_and_grad(self, loss_fn):
        """``step(packed, mbs, labels) -> (loss, packed_grads)`` — the
        reverse GPipe schedule falls out of differentiating the scan."""
        def loss_of(packed, mbs, labels):
            outs = self(packed, mbs)
            return jax.vmap(loss_fn)(outs, labels).mean()

        def step(packed, mbs, labels):
            return jax.value_and_grad(loss_of)(packed, mbs, labels)
        return step


def hetero_pipeline(stage_fns, stage_params, act_shapes, microbatch,
                    n_microbatches, mesh: Mesh, axis_name: str = "pp",
                    **kwargs):
    """Convenience constructor for :class:`HeteroPipeline`."""
    return HeteroPipeline(stage_fns, stage_params, act_shapes, microbatch,
                          n_microbatches, mesh, axis_name, **kwargs)
