"""Pipeline parallelism: microbatched GPipe stage loop.

The reference has no pipeline parallelism (SURVEY.md §2.3); provided as a
TPU-native capability. GPipe forward schedule expressed inside
``shard_map`` over the 'pp' mesh axis: each rank holds one stage's params
and an activation register; every tick it applies its stage and passes
the activation to the next rank via ``ppermute`` — XLA overlaps the ICI
hop with the next tick's compute.

Constraint of this schedule: all stages map activations of one shape to
the same shape (pad stage widths or wrap uneven stages accordingly).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

__all__ = ["pipeline_stage_loop"]


def pipeline_stage_loop(stage_fn, n_microbatches: int, mesh: Mesh,
                        axis_name: str = "pp"):
    """Build ``f(stage_params, microbatches) -> outputs``.

    - ``stage_params``: pytree whose leaves carry a leading pp-sharded
      stage axis (leaf shape (n_stages, ...)); rank i uses slice i.
    - ``microbatches``: (n_microbatches, mb, ...) replicated input; rank 0
      feeds them into the pipe in order.
    - returns (n_microbatches, mb, ...) — the last stage's outputs,
      broadcast to all ranks.
    """
    n_stages = mesh.shape[axis_name]
    ticks = n_stages + n_microbatches - 1

    def local(params, mbs):
        # shard_map hands each rank its stage slice with leading dim 1
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        rank = lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        reg = lax.pvary(jnp.zeros_like(mbs[0]), (axis_name,))
        out = lax.pvary(jnp.zeros_like(mbs), (axis_name,))

        def body(t, carry):
            reg, out = carry
            feed_idx = jnp.clip(t, 0, n_microbatches - 1)
            inp = jnp.where(rank == 0, mbs[feed_idx], reg)
            y = stage_fn(params, inp)
            # rank n-1 finishes microbatch t-(n_stages-1) at tick t
            done_idx = t - (n_stages - 1)
            valid = (done_idx >= 0) & (rank == n_stages - 1)
            slot = jnp.clip(done_idx, 0, n_microbatches - 1)
            out = out.at[slot].set(jnp.where(valid, y, out[slot]))
            reg = lax.ppermute(y, axis_name, perm)
            return reg, out

        reg, out = lax.fori_loop(0, ticks, body, (reg, out))
        # broadcast last rank's outputs to everyone
        out = jnp.where(rank == n_stages - 1, out, jnp.zeros_like(out))
        return lax.psum(out, axis_name)

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis_name), P()),
                     out_specs=P())
