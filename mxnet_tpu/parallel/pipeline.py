"""Pipeline parallelism: microbatched GPipe stage loop, trainable.

The reference has no pipeline parallelism (SURVEY.md §2.3); provided as
a TPU-native capability. GPipe forward schedule expressed inside
``shard_map`` over the 'pp' mesh axis: each rank holds one stage's
params and an activation register; every tick it applies its stage and
passes the activation to the next rank via ``ppermute`` — XLA overlaps
the ICI hop with the next tick's compute.

The tick loop is a ``lax.scan``, so the whole schedule is REVERSE-MODE
DIFFERENTIABLE: ``jax.grad`` of a loss on the pipe's outputs yields the
GPipe backward schedule automatically (the scan transpose runs the ticks
in reverse and the ``ppermute`` transpose sends cotangents across the
inverse permutation — backward activations flow last-stage -> first).
``pipeline_value_and_grad`` packages that into a training step.

Constraint of this schedule: all stages map activations of one shape to
the same shape (pad stage widths or wrap uneven stages accordingly).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

__all__ = ["pipeline_stage_loop", "pipeline_value_and_grad"]


def pipeline_stage_loop(stage_fn, n_microbatches: int, mesh: Mesh,
                        axis_name: str = "pp"):
    """Build ``f(stage_params, microbatches) -> outputs``.

    - ``stage_params``: pytree whose leaves carry a leading pp-sharded
      stage axis (leaf shape (n_stages, ...)); rank i uses slice i.
    - ``microbatches``: (n_microbatches, mb, ...) replicated input; rank 0
      feeds them into the pipe in order.
    - returns (n_microbatches, mb, ...) — the last stage's outputs,
      broadcast to all ranks.
    """
    n_stages = mesh.shape[axis_name]
    ticks = n_stages + n_microbatches - 1

    def local(params, mbs):
        # shard_map hands each rank its stage slice with leading dim 1
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        rank = lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        reg0 = lax.pvary(jnp.zeros_like(mbs[0]), (axis_name,))
        out0 = lax.pvary(jnp.zeros_like(mbs), (axis_name,))

        def tick(carry, t):
            reg, out = carry
            feed_idx = jnp.clip(t, 0, n_microbatches - 1)
            inp = jnp.where(rank == 0, mbs[feed_idx], reg)
            y = stage_fn(params, inp)
            # rank n-1 finishes microbatch t-(n_stages-1) at tick t
            done_idx = t - (n_stages - 1)
            valid = (done_idx >= 0) & (rank == n_stages - 1)
            slot = jnp.clip(done_idx, 0, n_microbatches - 1)
            out = out.at[slot].set(jnp.where(valid, y, out[slot]))
            reg = lax.ppermute(y, axis_name, perm)
            return (reg, out), None

        (reg, out), _ = lax.scan(tick, (reg0, out0),
                                 jnp.arange(ticks))
        # broadcast last rank's outputs to everyone
        out = jnp.where(rank == n_stages - 1, out, jnp.zeros_like(out))
        return lax.psum(out, axis_name)

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis_name), P()),
                     out_specs=P())


def pipeline_value_and_grad(stage_fn, loss_fn, n_microbatches: int,
                            mesh: Mesh, axis_name: str = "pp"):
    """Build a GPipe TRAINING step core:
    ``f(stage_params, microbatches, labels) -> (loss, grads)``.

    - ``loss_fn(outputs, labels) -> per-microbatch scalar`` is applied to
      each finished microbatch (labels shaped (n_microbatches, mb, ...));
      the reported loss is their mean.
    - ``grads`` has the same pp-sharded (n_stages, ...) structure as
      ``stage_params`` — each rank ends up holding exactly its own
      stage's gradients, computed by the reverse pipeline schedule that
      jax.grad derives from the forward scan.

    Wrap the result in ``jax.jit`` together with an optimizer update for
    a full pipeline-parallel train step (see tests/test_parallel.py and
    __graft_entry__.dryrun_multichip).
    """
    pipe = pipeline_stage_loop(stage_fn, n_microbatches, mesh,
                               axis_name=axis_name)

    def loss_of(params, mbs, labels):
        outs = pipe(params, mbs)
        per_mb = jax.vmap(loss_fn)(outs, labels)
        return per_mb.mean()

    def step(params, mbs, labels):
        return jax.value_and_grad(loss_of)(params, mbs, labels)

    return step
