"""Derive tensor-parallel PartitionSpecs from a gluon block's structure.

Replaces hand-written name-matchers: ``auto_spec(net, mesh)`` walks the
block tree and emits megatron-style shardings
(Megatron-LM, Shoeybi et al. 2019 — the standard column-then-row split
that needs one collective per attention/FFN pair):

- ``MultiHeadAttention``: query/key/value projections column-parallel
  (weight axis 0 — the heads dim), output projection row-parallel
  (weight axis 1); q/k/v biases shard with their rows, out bias
  replicated.
- expansion/contraction Dense pairs (FFN): any two Dense children of
  the same block where the first expands (units > in_units) and the
  second maps that width back down gets (column, row).
- ``Embedding``: vocab-sharded (weight axis 0).
- everything else replicated.

A dim is only sharded when divisible by the mesh axis size; otherwise
that param stays replicated (correct, just not distributed).

The reference has no analogue (its parallelism is replicated executors —
python/mxnet/module/executor_group.py); this is TPU-native design.
"""
from __future__ import annotations

from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["auto_spec"]


def _dense_shape(d):
    w = getattr(d, "weight", None)
    return None if w is None else tuple(w.shape)


def _walk_blocks(block):
    yield block
    for child in getattr(block, "_children", {}).values():
        yield from _walk_blocks(child)


def auto_spec(net, mesh: Mesh, axis: str = "tp"):
    """Return ``spec_fn(name, shape) -> PartitionSpec`` for
    ``ShardedTrainer(param_spec=...)``, derived from ``net``'s layer
    structure. ``net`` must be initialized (weight shapes known)."""
    from ..gluon.nn.attention import MultiHeadAttention
    from ..gluon.nn.basic_layers import Dense, Embedding

    specs = {}
    if axis not in mesh.shape:
        # no tensor-parallel axis on this mesh: everything replicates
        def spec_fn(name, shape):
            return P()
        spec_fn.specs = {}
        return spec_fn
    size = mesh.shape[axis]

    def col(d):
        """Column-parallel: split the output-units dim (weight axis 0
        in the (units, in_units) layout; bias shards with it)."""
        w = _dense_shape(d)
        if w and w[0] % size == 0:
            specs[d.weight.name] = P(axis, None)
            if getattr(d, "bias", None) is not None:
                specs[d.bias.name] = P(axis)

    def row(d):
        """Row-parallel: split the input dim (weight axis 1); bias is a
        post-reduce term and stays replicated."""
        w = _dense_shape(d)
        if w and len(w) == 2 and w[1] % size == 0:
            specs[d.weight.name] = P(None, axis)

    handled = set()
    for blk in _walk_blocks(net):
        if isinstance(blk, MultiHeadAttention):
            for d in (blk.query_proj, blk.key_proj, blk.value_proj):
                col(d)
                handled.add(id(d))
            row(blk.out_proj)
            handled.add(id(blk.out_proj))

    for blk in _walk_blocks(net):
        # FFN detection: consecutive Dense children (ignoring
        # activations/norms between) where the first expands and the
        # second consumes exactly that width
        denses = [c for c in getattr(blk, "_children", {}).values()
                  if isinstance(c, Dense) and id(c) not in handled]
        for d1, d2 in zip(denses, denses[1:]):
            if id(d1) in handled or id(d2) in handled:
                continue  # overlapping pairs must not re-spec a layer
            s1, s2 = _dense_shape(d1), _dense_shape(d2)
            if (s1 and s2 and len(s1) == 2 and len(s2) == 2
                    and s1[0] == s2[1] and s1[0] > s1[1]):
                col(d1)
                row(d2)
                handled.add(id(d1))
                handled.add(id(d2))

    for blk in _walk_blocks(net):
        if isinstance(blk, Embedding) and id(blk) not in handled:
            w = getattr(blk, "weight", None)
            if w is not None and tuple(w.shape)[0] % size == 0:
                specs[w.name] = P(axis, None)

    def spec_fn(name, shape):
        return specs.get(name, P())

    spec_fn.specs = dict(specs)  # introspectable for tests/debugging
    return spec_fn
