"""ShardedTrainer: one jitted SPMD train step over a Mesh.

TPU-native replacement for the reference's data-parallel training loop
(reference: python/mxnet/module/executor_group.py:144 per-GPU executors +
kvstore push/pull per weight, python/mxnet/gluon/trainer.py:329). The
whole step — forward, backward, gradient allreduce, optimizer update — is
ONE compiled XLA program: gradients never materialize per-replica; XLA
lowers the mean over 'dp' to a psum on ICI and fuses the optimizer update
into it. Buffers are donated, so weights update in place in HBM (the
reference needed kWriteInplace optimizer kernels for this).

Placement (``mesh.place_global`` / ``batch_spec`` / ``leaf_spec``) and
the ``mxtpu_spmd_*`` evidence series are shared with
``jit.CompiledTrainStep``'s mesh mode — one SPMD machinery, two front
ends (functional here, gluon-Trainer there). lr/wd enter the step as
traced scalars, so schedules never recompile; the remaining optimizer
hyperparameters bake at first trace.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as _np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import optimizer as opt_mod
from ..ndarray import NDArray
from .functional import functional_call, extract_params, load_params
from .mesh import (local_mesh, leaf_spec, place_global as _to_global,
                   round_up_to_dp, spans_processes as _spans_processes,
                   spmd_metrics, note_mesh, to_host as _to_host)

__all__ = ["ShardedTrainer", "shard_batch"]


def shard_batch(x, mesh: Mesh, axis: str = "dp"):
    """Place a host batch as one global array sharded on the batch dim
    (≙ gluon.utils.split_and_load, reference gluon/utils.py:95 — but one
    array, not per-device copies)."""
    arr = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    spec = P(axis, *([None] * (arr.ndim - 1)))
    return NDArray(_to_global(arr, mesh, spec, host_has="local_shard"))


class ShardedTrainer:
    """Train a Gluon block under pjit-style sharding.

    Parameters
    ----------
    block : initialized (possibly un-hybridized) gluon Block
    loss_fn : gluon loss block or callable (pred, label) -> per-sample loss
    optimizer : name or Optimizer instance (the same zoo Trainer uses)
    mesh : jax Mesh (default: 1-axis dp mesh over all devices)
    param_spec : optional callable (name, shape) -> PartitionSpec for
        tensor-parallel weight sharding; default replicates params.

    Notes
    -----
    lr and wd enter the compiled step as traced scalars — schedules and
    ``set_learning_rate`` never recompile. The remaining optimizer
    hyperparameters (momentum, betas, eps, ...) bake at first trace.
    The reference pays a kernel launch per parameter per step instead.
    """

    def __init__(self, block, loss_fn, optimizer="sgd",
                 optimizer_params=None, mesh: Optional[Mesh] = None,
                 param_spec: Optional[Callable] = None, donate=True):
        self._block = block
        self._loss_fn = loss_fn
        self._mesh = mesh if mesh is not None else local_mesh()
        if isinstance(optimizer, str):
            self._optimizer = opt_mod.create(optimizer,
                                             **(optimizer_params or {}))
        else:
            # private copy: the traced step counter seeded into
            # _index_update_count must not leak into an eager Trainer
            # sharing the same instance
            import copy
            self._optimizer = copy.copy(optimizer)
            self._optimizer._index_update_count = {}
        self._param_spec = param_spec
        self._donate = donate
        self._step_jit = None
        self._step_count = 0
        self._rngkey = jax.random.key(0)
        self._params = None
        self._restore_pending = None
        # training-side batch-tail bucketing (shared policy with
        # jit.CompiledTrainStep): ragged final batches pad to a
        # power-of-two bucket instead of retracing the SPMD program; a
        # mask from the traced real-row count keeps the loss mean exact
        from ..jit import step_buckets_config
        self._buckets = step_buckets_config()
        self._max_batch = 0
        self._loss_scalar = None   # discovered at first trace
        self._ckpt_mgrs = {}       # realpath(run_dir) -> CheckpointManager

    def _ensure_init(self, x):
        if self._params is not None:
            return
        block = self._block
        plist = block.collect_params()
        if any(p._data is None and (p.shape is None or 0 in p.shape)
               for p in plist.values()):
            # one eager predict pass resolves deferred shapes
            from .. import autograd
            with autograd.pause(train_mode=False):
                block(NDArray(jnp.asarray(x)[:1]))
        params = extract_params(block)
        self._names = sorted(params)
        self._trainable = [
            n for n in self._names
            if block.collect_params()[n].grad_req != "null"]
        # shard/replicate parameters onto the mesh; leaf_spec clamps a
        # requested spec to what the shape/mesh actually divides, so a
        # param_spec over an indivisible dim degrades to replicated
        # instead of a placement error
        specs = {n: leaf_spec(
                     self._param_spec(n, params[n].shape)
                     if self._param_spec else P(),
                     tuple(params[n].shape), self._mesh)
                 for n in self._names}
        self._params = {n: _to_global(params[n], self._mesh, specs[n])
                        for n in self._names}
        # optimizer states live with their parameter, same sharding
        # (weight-shaped slots; anything else replicates via leaf_spec)
        self._opt_states = {}
        for i, n in enumerate(self._trainable):
            st = self._optimizer.create_state(i, NDArray(params[n]))
            self._opt_states[n] = jax.tree_util.tree_map(
                lambda a, s=specs[n]: _to_global(
                    a._data if isinstance(a, NDArray) else a, self._mesh,
                    leaf_spec(s, tuple(a.shape), self._mesh)), st,
                is_leaf=lambda a: isinstance(a, NDArray))
        self._specs = specs
        # logical per-step gradient-psum payload over dp (one grad the
        # size of every trainable weight), for mxtpu_spmd_collective_*
        self._grad_bytes = sum(
            int(self._params[n].size) * self._params[n].dtype.itemsize
            for n in self._trainable)
        note_mesh(self._mesh)
        if self._restore_pending is not None:
            self._apply_restore(self._restore_pending)
            self._restore_pending = None

    @property
    def params(self):
        return self._params

    def _build_step(self):
        block, loss_fn, optimizer = self._block, self._loss_fn, \
            self._optimizer
        trainable = self._trainable
        mesh, specs = self._mesh, self._specs

        trainer = self

        def step(params, opt_states, hyper, rng, t, n_real, x, y):
            def objective(trn_params):
                full = dict(params)
                full.update(trn_params)
                out, aux = functional_call(block, full, x, training=True,
                                           rng=rng)
                loss = loss_fn(NDArray(out), NDArray(y))
                lv = loss._data
                trainer._loss_scalar = (lv.ndim == 0)
                if lv.ndim == 0:
                    return lv, aux
                # masked mean over the REAL rows: identical to .mean()
                # at full buckets (×1.0 then the same sum; the divisor
                # value is equal), pad-row-proof at ragged tails
                mask = (jnp.arange(lv.shape[0]) < n_real).astype(
                    lv.dtype).reshape((lv.shape[0],)
                                      + (1,) * (lv.ndim - 1))
                per_row = lv.size // lv.shape[0]
                return (lv * mask).sum() / (n_real * per_row), aux

            (loss, aux), grads = jax.value_and_grad(
                objective, has_aux=True)({n: params[n] for n in trainable})

            new_params = dict(params)
            new_states = {}
            # lr/wd ride as traced scalars so schedules and manual
            # set_learning_rate never recompile the SPMD program; the
            # scheduler (host state) is evaluated OUTSIDE the trace.
            # num_update/_index_update_count are restored too — the
            # traced t seeds them below, and a tracer left behind would
            # kill the next step's host-side scheduler sync
            saved = (optimizer.lr, optimizer.wd, optimizer.lr_scheduler,
                     optimizer.num_update,
                     dict(optimizer._index_update_count))
            optimizer.lr, optimizer.wd = hyper
            optimizer.lr_scheduler = None
            try:
                for i, n in enumerate(trainable):
                    w = NDArray(params[n])
                    g = NDArray(grads[n])
                    st = jax.tree_util.tree_map(NDArray, opt_states[n])
                    # seed the update count with the TRACED step so
                    # Adam-family bias correction uses the true t under
                    # jit (the Python counter would bake t=1 into the
                    # compiled program)
                    optimizer._index_update_count[i] = t - 1
                    optimizer.update_multi_precision(i, w, g, st)
                    new_params[n] = w._data
                    new_states[n] = jax.tree_util.tree_map(
                        lambda a: a._data if isinstance(a, NDArray)
                        else a, st,
                        is_leaf=lambda a: isinstance(a, NDArray))
            finally:
                (optimizer.lr, optimizer.wd, optimizer.lr_scheduler,
                 optimizer.num_update) = saved[:4]
                optimizer._index_update_count.clear()
                optimizer._index_update_count.update(saved[4])
            # aux states (BN running stats) ride along, replicated
            for n, v in aux.items():
                new_params[n] = v
            # pin outputs to their input shardings: donated buffers
            # alias and the next step's inputs need no reshard (GSPMD
            # would otherwise be free to pick another output layout)
            new_params = {
                n: jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, specs.get(n, P())))
                for n, v in new_params.items()}
            new_states = {
                n: jax.tree_util.tree_map(
                    lambda a, s=specs[n]: jax.lax.with_sharding_constraint(
                        a, NamedSharding(mesh, leaf_spec(
                            s, tuple(a.shape), mesh))), st)
                for n, st in new_states.items()}
            return new_params, new_states, loss

        donate = (0, 1) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def _obs_metrics(self):
        obs = getattr(self, "_obs", None)
        if obs is None:
            from ..observability import get_registry
            reg = get_registry()
            obs = self._obs = {
                "steps": reg.counter(
                    "mxtpu_training_sharded_steps_total",
                    "ShardedTrainer SPMD steps dispatched."),
                "secs": reg.histogram(
                    "mxtpu_training_sharded_step_seconds",
                    "Host-side dispatch time of one SPMD step (async: "
                    "excludes on-device time unless the loss is "
                    "fetched)."),
                "examples": reg.counter(
                    "mxtpu_training_examples_total",
                    "Examples processed (sum of Trainer.step "
                    "batch sizes)."),
            }
            # the SPMD step is a compiled whole-step program too: it
            # reports on the same mxtpu_train_step_* series the
            # jit.CompiledTrainStep path feeds, plus the shared
            # mxtpu_spmd_* evidence series
            from ..jit import _metrics as _step_metrics
            obs.update(_step_metrics())
            obs["spmd"] = spmd_metrics()
        return obs

    def _pick_bucket(self, n, can_pad):
        """Bucket for this batch: powers of two up to the largest batch
        seen (jit.CompiledTrainStep's policy), rounded up to the mesh's
        dp extent so the batch axis stays evenly shardable. Padding is
        held off until the first trace proved the loss is per-sample
        (a pre-reduced scalar loss cannot be pad-corrected)."""
        self._max_batch = max(self._max_batch, n)
        if not can_pad or self._buckets is None \
                or self._loss_scalar is not False:
            return n
        from ..jit import pick_train_bucket
        b = pick_train_bucket(n, self._buckets, self._max_batch)
        return round_up_to_dp(b, self._mesh)

    @staticmethod
    def _pad_rows(v, bucket):
        from ..jit import pad_rows
        return pad_rows(v, bucket)

    def step(self, x, y):
        """One SPMD training step; returns the (replicated) scalar loss."""
        import time as _time
        obs = self._obs_metrics()
        t0 = _time.monotonic()
        self._ensure_init(x)
        if self._step_jit is None:
            self._step_jit = self._build_step()
        presharded_x = isinstance(x, NDArray) and _is_sharded(x._data)
        presharded_y = isinstance(y, NDArray) and _is_sharded(y._data)
        n = int(x.shape[0])
        can_pad = not (presharded_x or presharded_y) \
            and not _spans_processes(self._mesh)
        bucket = self._pick_bucket(n, can_pad)
        if bucket != n:
            x, y = self._pad_rows(x, bucket), self._pad_rows(y, bucket)
            obs["padded_rows"].inc(bucket - n)
        xb = shard_batch(x, self._mesh)._data if not presharded_x \
            else x._data
        yb = shard_batch(y, self._mesh)._data if not presharded_y \
            else y._data
        self._rngkey, sub = jax.random.split(self._rngkey)
        t = jnp.asarray(self._step_count + 1, jnp.float32)
        opt = self._optimizer
        if opt.lr_scheduler is not None:
            # schedules key off num_update, which only the eager path
            # advances — sync it to the traced step count so a restored
            # run resumes its schedule at the right position
            opt.num_update = max(opt.num_update, self._step_count)
        # plain python floats: jit traces them as weak-typed scalars, so
        # every lr/wd value reuses the same compiled program
        hyper = (float(opt.learning_rate), float(opt.wd))
        cache_size = getattr(self._step_jit, "_cache_size", None)
        progs0 = cache_size() if callable(cache_size) else None
        self._params, self._opt_states, loss = self._step_jit(
            self._params, self._opt_states, hyper, sub, t, n, xb, yb)
        self._step_count += 1
        obs["secs"].observe(_time.monotonic() - t0)
        obs["steps"].inc()
        obs["dispatch"].inc()
        obs["compiled"].inc()
        obs["examples"].inc(n)  # real rows, not the padded bucket
        sobs = obs["spmd"]
        sobs["dispatch"].inc()
        if progs0 is not None and cache_size() > progs0:
            sobs["programs"].labels(
                devices=str(self._mesh.devices.size),
                bucket=str(bucket)).inc()
        if dict(self._mesh.shape).get("dp", 1) > 1:
            sobs["bytes"].labels(collective="grad_reduce").inc(
                self._grad_bytes)
        from ..resilience import faults
        from ..resilience import async_writer as _aw
        _aw.note_step_overlap()
        faults.on_step(self._step_count)
        if _spans_processes(self._mesh):
            # the loss is replicated; hand back this process's copy so
            # eager reads (asscalar) need no cross-host fetch
            loss = loss.addressable_data(0)
        return NDArray(loss)

    def forward(self, x, training=False):
        """Sharded inference through the current parameters."""
        self._ensure_init(x)
        xb = shard_batch(x, self._mesh)._data
        out, _ = functional_call(self._block, self._params, xb,
                                 training=training)
        return NDArray(out)

    def sync_block(self):
        """Write trained parameters back into the Gluon block."""
        load_params(self._block, self._params)

    # -------------------------------------------------- full-state ckpt --
    def save_state(self, run_dir, epoch=None, keep=5, num_shards=None):
        """Commit the full sharded training state to a crash-safe
        checkpoint directory (resilience.checkpoint layout): parameters,
        every optimizer slot, the trainer's PRNG key, and the step
        counter. Arrays are written as full host values (sharding is a
        placement property, not a value property), so a checkpoint can
        be restored under a different mesh/param_spec — and with the
        sharded v2 layout (``MXNET_TPU_CKPT_SHARDED`` / ``num_shards=``)
        they land as parallel per-shard row files whose manifest records
        the global tree, so restore reshards to ANY mesh size. Async
        mode (``MXNET_TPU_CKPT_ASYNC=1``) snapshots here and
        serializes on a background writer (``ckpt_wait()`` joins). Only
        process 0 writes. Returns the checkpoint path / async handle
        (None if uninitialized)."""
        from ..resilience import checkpoint as ckpt
        from .mesh import mesh_shard_info
        if self._params is None:
            return None
        # keyed by position in the sorted name list, not by raw name:
        # gluon name prefixes auto-increment per process, so a restarted
        # process re-creating the same architecture gets shifted names —
        # sorted position is stable, and names ride in `extra` for
        # diagnostics
        arrays = {}
        for idx, n in enumerate(self._names):
            arrays[f"param:{idx}"] = NDArray(_to_host(self._params[n]))
        opt_structs = []
        for tidx, n in enumerate(self._trainable):
            leaves = jax.tree_util.tree_leaves(self._opt_states[n])
            opt_structs.append(len(leaves))
            for i, leaf in enumerate(leaves):
                arrays[f"opt:{tidx}:{i}"] = NDArray(_to_host(leaf))
        extra = {
            "trainer": "sharded",
            "step_count": self._step_count,
            "rng_key": _np.asarray(
                jax.random.key_data(self._rngkey)).tolist(),
            "opt_leaf_counts": opt_structs,
            "param_names": list(self._names),
            # the mesh that SAVED: elastic resume reads this for
            # diagnostics/placement hints, never as a constraint
            "mesh": mesh_shard_info(self._mesh),
            "max_batch": int(self._max_batch),
        }
        mgr = ckpt.manager_for(self._ckpt_mgrs, run_dir, keep=keep,
                               num_shards=num_shards)
        return mgr.save(arrays, step=self._step_count, epoch=epoch,
                        extra=extra)

    def ckpt_wait(self):
        """Join in-flight async checkpoint saves; drains ALL run dirs
        before raising the FIRST failure."""
        first = None
        for mgr in self._ckpt_mgrs.values():
            try:
                mgr.wait()
            except BaseException as exc:   # noqa: B036 — InjectedCrash
                if first is None:
                    first = exc
        if first is not None:
            raise first

    def restore_state(self, run_dir):
        """Load the newest valid checkpoint under ``run_dir``. Before
        the first step the restore is deferred and applied inside
        ``_ensure_init`` (parameter shapes/specs only exist then); after
        initialization it applies immediately. Either way the next
        ``step()`` continues bit-exactly from the checkpointed state.
        Returns the manifest."""
        from .. import error
        from ..resilience import checkpoint as ckpt
        path, manifest = ckpt.latest_checkpoint(run_dir)
        if path is None:
            raise error.CheckpointCorruptError(
                f"'{run_dir}': no restorable checkpoint found")
        arrays = ckpt.read_arrays(path, manifest)
        extra = manifest.get("extra", {})
        state = {"arrays": arrays, "extra": extra}
        if self._params is None:
            self._restore_pending = state
        else:
            self._apply_restore(state)
        return manifest

    def _apply_restore(self, state):
        arrays, extra = state["arrays"], state["extra"]
        from .. import error
        for idx, n in enumerate(self._names):
            key = f"param:{idx}"
            if key not in arrays:
                raise error.InternalError(
                    f"checkpoint is missing parameter #{idx} ('{n}')")
            v = arrays[key]._data
            if tuple(v.shape) != tuple(self._params[n].shape):
                raise error.InternalError(
                    f"checkpoint parameter #{idx} ('{n}') has shape "
                    f"{tuple(v.shape)}, model expects "
                    f"{tuple(self._params[n].shape)}")
            self._params[n] = _to_global(v, self._mesh, self._specs[n])
        counts = extra.get("opt_leaf_counts", [])
        for tidx, n in enumerate(self._trainable):
            leaves, treedef = jax.tree_util.tree_flatten(
                self._opt_states[n])
            want = int(counts[tidx]) if tidx < len(counts) \
                else len(leaves)
            if want != len(leaves):
                raise error.InternalError(
                    f"checkpoint optimizer state for '{n}' has {want} "
                    f"slots, current optimizer expects {len(leaves)} — "
                    "restore with the same optimizer family")
            new_leaves = []
            for i in range(len(leaves)):
                key = f"opt:{tidx}:{i}"
                if key not in arrays:
                    raise error.InternalError(
                        f"checkpoint is missing optimizer slot '{key}'")
                new_leaves.append(_to_global(arrays[key]._data,
                                             self._mesh, self._specs[n]))
            self._opt_states[n] = jax.tree_util.tree_unflatten(
                treedef, new_leaves)
        self._step_count = int(extra.get("step_count", 0))
        # bucket warmth from the saved run: resumed ragged tails pad to
        # the same buckets the uninterrupted run would have used
        self._max_batch = max(self._max_batch,
                              int(extra.get("max_batch", 0) or 0))
        if extra.get("rng_key") is not None:
            self._rngkey = jax.random.wrap_key_data(
                jnp.asarray(_np.asarray(extra["rng_key"],
                                        dtype=_np.uint32)))


def _is_sharded(arr):
    try:
        return len(arr.devices()) > 1
    except Exception:
        return False
