"""Device mesh construction.

Reference analogue: the kvstore/comm topology machinery
(src/kvstore/comm_tree.h:50 ComputeTrees builds reduction trees from the
PCIe/NVLink link matrix). On TPU none of that exists: the ICI fabric is a
torus XLA already knows; we only pick logical axis sizes.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "local_mesh", "data_parallel_spec",
           "mesh_shard_info"]


def make_mesh(dp: Optional[int] = None, tp: int = 1, pp: int = 1,
              sp: int = 1, ep: int = 1, devices=None) -> Mesh:
    """Build a Mesh with named axes (dp, tp, pp, sp, ep); ``dp=None``
    absorbs all remaining devices.

    The axis order places dp outermost so data-parallel allreduce rides
    the widest rings, with tp innermost (finest-grained collectives on
    nearest neighbors) — the standard ICI layout recipe."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = tp * pp * sp * ep
    if dp is None:
        assert n % fixed == 0, \
            f"{n} devices not divisible by tp*pp*sp*ep={fixed}"
        dp = n // fixed
    total = dp * fixed
    assert total <= n, f"requested {total} devices, have {n}"
    arr = _np.array(devices[:total]).reshape(dp, pp, sp, ep, tp)
    return Mesh(arr, ("dp", "pp", "sp", "ep", "tp"))


def local_mesh(n: Optional[int] = None) -> Mesh:
    """1-axis dp mesh over local devices — the moral equivalent of
    kvstore 'device' (single-host data parallel)."""
    devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return Mesh(_np.array(devices), ("dp",))


def data_parallel_spec(ndim: int) -> PartitionSpec:
    """PartitionSpec sharding axis0 (batch) on dp, rest replicated."""
    return PartitionSpec("dp", *([None] * (ndim - 1)))


def mesh_shard_info(mesh: Mesh) -> dict:
    """Checkpoint-facing shard layout metadata for a mesh: how many
    parallel checkpoint shards the mesh naturally supports (one per
    participating process), which shard this process owns, and the
    logical axis extents — recorded in sharded-checkpoint manifests so
    an elastic resume knows what world wrote the state it is reading
    (``resilience.sharded`` plans its row layout from this count when
    ``MXNET_TPU_CKPT_SHARDED=auto``)."""
    procs = sorted({d.process_index for d in mesh.devices.flat})
    me = jax.process_index()
    return {
        "num_shards": len(procs),
        "shard_id": procs.index(me) if me in procs else 0,
        "axes": {k: int(v) for k, v in dict(mesh.shape).items()},
        "num_devices": int(mesh.devices.size),
    }
