"""Device mesh construction.

Reference analogue: the kvstore/comm topology machinery
(src/kvstore/comm_tree.h:50 ComputeTrees builds reduction trees from the
PCIe/NVLink link matrix). On TPU none of that exists: the ICI fabric is a
torus XLA already knows; we only pick logical axis sizes.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "local_mesh", "data_parallel_spec",
           "mesh_shard_info", "parse_mesh", "llm_mesh", "batch_spec",
           "leaf_spec", "round_up_to_dp", "spans_processes",
           "place_global", "to_host", "spmd_metrics", "note_mesh"]


def make_mesh(dp: Optional[int] = None, tp: int = 1, pp: int = 1,
              sp: int = 1, ep: int = 1, devices=None) -> Mesh:
    """Build a Mesh with named axes (dp, tp, pp, sp, ep); ``dp=None``
    absorbs all remaining devices.

    The axis order places dp outermost so data-parallel allreduce rides
    the widest rings, with tp innermost (finest-grained collectives on
    nearest neighbors) — the standard ICI layout recipe."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = tp * pp * sp * ep
    if dp is None:
        assert n % fixed == 0, \
            f"{n} devices not divisible by tp*pp*sp*ep={fixed}"
        dp = n // fixed
    total = dp * fixed
    assert total <= n, f"requested {total} devices, have {n}"
    arr = _np.array(devices[:total]).reshape(dp, pp, sp, ep, tp)
    return Mesh(arr, ("dp", "pp", "sp", "ep", "tp"))


def local_mesh(n: Optional[int] = None) -> Mesh:
    """1-axis dp mesh over local devices — the moral equivalent of
    kvstore 'device' (single-host data parallel)."""
    devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return Mesh(_np.array(devices), ("dp",))


def data_parallel_spec(ndim: int) -> PartitionSpec:
    """PartitionSpec sharding axis0 (batch) on dp, rest replicated."""
    return PartitionSpec("dp", *([None] * (ndim - 1)))


def parse_mesh(spec, devices=None) -> Mesh:
    """Build a Mesh from a compact string spec — the CLI/env spelling of
    :func:`make_mesh` (``bench.py --mesh``, ``MXNET_TPU_MESH``):

    - ``"8"``            → 1-axis dp mesh over 8 devices
    - ``"dp=4,tp=2"``    → named axis extents (unnamed axes default 1)
    - ``"dp=-1,tp=2"``   → dp absorbs the remaining devices
    """
    spec = str(spec).strip()
    if not spec:
        return local_mesh()
    if spec.isdigit():
        return local_mesh(int(spec))
    axes = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        k, v = k.strip(), v.strip()
        if k not in ("dp", "tp", "pp", "sp", "ep"):
            raise ValueError(f"unknown mesh axis {k!r} in {spec!r} "
                             "(axes: dp, tp, pp, sp, ep)")
        axes[k] = int(v)
    dp = axes.pop("dp", None)
    if dp is not None and dp < 0:
        dp = None
    return make_mesh(dp=dp, devices=devices, **axes)


def llm_mesh(spec, devices=None) -> Mesh:
    """Build the serving mesh from a compact string spec — the
    CLI/env spelling for the LLM engine (``llm_bench.py --mesh``,
    ``MXNET_TPU_LLM_MESH``). Same axis grammar as :func:`parse_mesh`
    but with SERVING defaults: only ``dp``/``tp`` axes exist, a bare
    integer means tensor-parallel width, and ``dp`` defaults to 1
    instead of absorbing leftover devices (an engine that silently
    grew replica groups because the host had spare chips would break
    the one-scheduler accounting; ask for dp explicitly).

    - ``"tp=2"``       → 1x2 (dp, tp) mesh
    - ``"2"``          → tp=2
    - ``"dp=2,tp=2"``  → 2 replica groups of 2-way tensor parallel
    - ``"dp=-1,tp=2"`` → dp absorbs the remaining devices
    """
    spec = str("" if spec is None else spec).strip()
    axes = {"dp": 1, "tp": 1}
    if spec.isdigit():
        axes["tp"] = int(spec)
    elif spec:
        for part in spec.split(","):
            k, _, v = part.partition("=")
            k, v = k.strip(), v.strip()
            if k not in axes:
                raise ValueError(f"unknown llm mesh axis {k!r} in "
                                 f"{spec!r} (axes: dp, tp)")
            axes[k] = int(v)
    devices = list(devices if devices is not None else jax.devices())
    dp, tp = axes["dp"], axes["tp"]
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if dp < 0:
        if len(devices) % tp:
            raise ValueError(f"{len(devices)} devices not divisible "
                             f"by tp={tp}")
        dp = len(devices) // tp
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    total = dp * tp
    if total > len(devices):
        raise ValueError(f"llm mesh dp={dp},tp={tp} needs {total} "
                         f"devices, have {len(devices)}")
    arr = _np.array(devices[:total]).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))


# ----------------------------------------------------------- placement --
# The SPMD train step (jit.CompiledTrainStep mesh mode / ShardedTrainer)
# places every program input through these helpers so single-process and
# multi-process meshes share one code path.

@functools.lru_cache(maxsize=64)
def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh covers devices of more than this process.
    Cached: scanning ``mesh.devices.flat`` in Python on every step would
    cost thousands of attribute reads per step on big slices."""
    pid = jax.process_index()
    return any(d.process_index != pid for d in mesh.devices.flat)


def batch_spec(ndim: int, mesh: Mesh, rows: int,
               axis: str = "dp") -> PartitionSpec:
    """PartitionSpec for a batch-major program input: axis 0 sharded on
    ``dp`` when the mesh has a dp extent > 1 that divides ``rows``,
    replicated otherwise (an indivisible batch is still correct SPMD —
    every device just sees the full batch and no gradient psum is
    emitted)."""
    dp = dict(mesh.shape).get(axis, 1)
    if ndim == 0 or dp <= 1 or rows % dp:
        return PartitionSpec()
    return PartitionSpec(axis, *([None] * (ndim - 1)))


def leaf_spec(spec: PartitionSpec, shape, mesh: Mesh) -> PartitionSpec:
    """Clamp a parameter's PartitionSpec onto an array of ``shape`` —
    optimizer slots ride with their parameter's spec when they are
    weight-shaped, and fall back to replicated when they are not (scalar
    slots, per-row norms) or when a sharded dim is not divisible by its
    mesh axis extent."""
    spec = tuple(spec or ())
    if not spec or all(ax is None for ax in spec):
        return PartitionSpec()
    if len(spec) != len(shape):
        return PartitionSpec()
    extents = dict(mesh.shape)
    out = []
    for dim, ax in zip(shape, spec):
        if ax is None:
            out.append(None)
            continue
        size = 1
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            size *= extents.get(a, 1)
        out.append(ax if size > 1 and dim % size == 0 else None)
    if all(ax is None for ax in out):
        return PartitionSpec()
    return PartitionSpec(*out)


def round_up_to_dp(bucket: int, mesh: Mesh, axis: str = "dp") -> int:
    """Round a batch bucket up to a multiple of the mesh's dp extent so
    the batch axis stays evenly shardable (pad rows are masked by the
    train step's traced real-row count)."""
    dp = dict(mesh.shape).get(axis, 1)
    if dp > 1 and bucket % dp:
        bucket += dp - (bucket % dp)
    return bucket


@functools.lru_cache(maxsize=4096)
def _named_sharding(mesh: Mesh, spec: PartitionSpec) -> NamedSharding:
    # the per-step placement sweep (jit._place_mesh/_place_nt,
    # ShardedTrainer.step) calls place_global for every weight and
    # optimizer slot on every step; caching the NamedSharding keeps
    # that steady-state no-op path at a dict hit + equality check per
    # leaf instead of an object construction
    return NamedSharding(mesh, spec)


def _placed_as(arr, sharding) -> bool:
    try:
        return arr.sharding == sharding
    except AttributeError:
        return False


def place_global(arr, mesh: Mesh, spec: PartitionSpec,
                 host_has: str = "full"):
    """Place a value onto ``mesh`` as one global array with ``spec``
    sharding; a no-op when it already lives there. Within one process
    this is a plain ``device_put``. Across processes the meaning of the
    host value matters (``host_has``):

    - ``"full"``: every process holds the whole (global-shape) value —
      parameters/optimizer state. Replicated specs broadcast rank 0's
      values (the reference dist_sync init semantics: kvstore_dist.h
      Init pushes rank-0 weights), so ranks cannot silently train on
      divergent 'replicated' parameters; sharded specs slice each
      process's addressable shards out of its full copy
      (make_array_from_callback) — NOT concatenation.
    - ``"local_shard"``: each process holds only its own piece —
      batches. The global array is the concatenation of every process's
      local array along the sharded axis
      (host_local_array_to_global_array), the reference's dist_sync
      data layout."""
    sharding = _named_sharding(mesh, spec)
    if _placed_as(arr, sharding):
        return arr
    if spans_processes(mesh):
        from jax.experimental import multihost_utils
        arr = _np.asarray(arr)
        replicated = all(ax is None for ax in (spec or ())) \
            or spec == PartitionSpec()
        if host_has == "full":
            if replicated:
                arr = multihost_utils.broadcast_one_to_all(arr)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])
        return multihost_utils.host_local_array_to_global_array(
            arr, mesh, spec)
    return jax.device_put(arr, sharding)


def to_host(arr) -> _np.ndarray:
    """Full host value of a (possibly sharded) global array. Fully
    addressable arrays are a plain device_get; multi-process global
    arrays need the allgather (only the checkpoint writer pays it)."""
    try:
        addressable = arr.is_fully_addressable
    except AttributeError:
        addressable = True
    if addressable:
        return _np.asarray(jax.device_get(arr))
    from jax.experimental import multihost_utils
    return _np.asarray(multihost_utils.process_allgather(arr, tiled=True))


# ------------------------------------------------------------- metrics --

_SPMD_OBS = None


def spmd_metrics() -> dict:
    """The ``mxtpu_spmd_*`` series: evidence that multi-chip training is
    ONE program per step (dispatch count), what it moves over ICI
    (collective bytes), and what mesh it runs on (shape gauges)."""
    global _SPMD_OBS
    if _SPMD_OBS is None:
        from ..observability import get_registry
        reg = get_registry()
        _SPMD_OBS = {
            "dispatch": reg.counter(
                "mxtpu_spmd_step_dispatch_total",
                "SPMD whole-step program launches (steady state: exactly "
                "1 per training step at any device count)."),
            "programs": reg.counter(
                "mxtpu_spmd_program_compiles_total",
                "SPMD whole-step program builds, by (devices, bucket) — "
                "flat after warmup = zero steady-state recompiles.",
                ("devices", "bucket")),
            "bytes": reg.counter(
                "mxtpu_spmd_collective_bytes_total",
                "Logical in-program collective payload, by collective "
                "kind (grad_reduce = per-step gradient psum bytes over "
                "the dp axis; XLA may further shard/fuse the actual ICI "
                "transfers).", ("collective",)),
            "devices": reg.gauge(
                "mxtpu_spmd_mesh_devices",
                "Device count of the mesh the last SPMD step program "
                "was built for."),
            "axis": reg.gauge(
                "mxtpu_spmd_mesh_axis_extent",
                "Logical axis extents of the active SPMD mesh.",
                ("axis",)),
        }
    return _SPMD_OBS


def note_mesh(mesh: Mesh) -> None:
    """Publish the mesh shape on the ``mxtpu_spmd_mesh_*`` gauges."""
    obs = spmd_metrics()
    obs["devices"].set(int(mesh.devices.size))
    for ax, extent in dict(mesh.shape).items():
        obs["axis"].labels(axis=ax).set(int(extent))


def mesh_shard_info(mesh: Mesh) -> dict:
    """Checkpoint-facing shard layout metadata for a mesh: how many
    parallel checkpoint shards the mesh naturally supports (one per
    participating process), which shard this process owns, and the
    logical axis extents — recorded in sharded-checkpoint manifests so
    an elastic resume knows what world wrote the state it is reading
    (``resilience.sharded`` plans its row layout from this count when
    ``MXNET_TPU_CKPT_SHARDED=auto``)."""
    procs = sorted({d.process_index for d in mesh.devices.flat})
    me = jax.process_index()
    return {
        "num_shards": len(procs),
        "shard_id": procs.index(me) if me in procs else 0,
        "axes": {k: int(v) for k, v in dict(mesh.shape).items()},
        "num_devices": int(mesh.devices.size),
    }
