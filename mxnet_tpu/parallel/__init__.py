"""TPU-native parallelism: meshes, sharded training, ring attention.

This package is the TPU-idiomatic replacement for the reference's entire
distributed stack (SURVEY.md §2.3/§2.4: DataParallelExecutorGroup slicing,
kvstore local/device/tree reducers, NCCL, ps-lite PS, Horovod, P3):
instead of replicating executors and pushing gradients through a store,
ONE jitted SPMD program runs over a ``jax.sharding.Mesh`` and XLA inserts
the collectives (psum/all-gather/reduce-scatter) over ICI/DCN.

Axes convention (How-to-Scale-Your-Model recipe):
  dp — data parallel (batch dim)     tp — tensor parallel (weight shards)
  pp — pipeline stages               sp — sequence/context parallel
  ep — expert parallel
"""
from .compat import shard_map  # noqa: F401  (version-proof import path)
from .mesh import (make_mesh, local_mesh, data_parallel_spec,  # noqa: F401
                   mesh_shard_info, parse_mesh, llm_mesh)  # noqa: F401
from .functional import functional_call, extract_params, load_params  # noqa: F401
from .trainer import ShardedTrainer, shard_batch  # noqa: F401
from .ring_attention import ring_attention, sequence_shard  # noqa: F401
from .pipeline import (pipeline_stage_loop,  # noqa: F401
                       pipeline_value_and_grad,  # noqa: F401
                       hetero_pipeline, HeteroPipeline)  # noqa: F401
from .stages import gluon_pipeline_stages  # noqa: F401
from .auto_spec import auto_spec  # noqa: F401
