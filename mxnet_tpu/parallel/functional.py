"""Functional bridge: run a Gluon block as a pure function of its params.

The sharded/pjit training path needs ``f(params, x) -> y`` purity; Gluon
blocks hold parameters internally. This bridge reuses the trace machinery
of gluon.block.CachedOp: parameter reads are redirected to caller-supplied
arrays, aux-state writes (BatchNorm running stats) are captured and
returned (reference aux states are engine-mutated in place,
src/operator/nn/batch_norm.cc; here they thread functionally).
"""
from __future__ import annotations

from typing import Dict

import jax

from .. import autograd, _rng
from ..ndarray import NDArray
from ..gluon.parameter import _TRACE_STACK
from ..gluon.block import _suspend_hybridization

__all__ = ["functional_call", "extract_params", "load_params"]


def extract_params(block) -> Dict[str, jax.Array]:
    """Pull the block's parameter values as a flat {name: jax.Array}."""
    out = {}
    for name, p in block.collect_params().items():
        p._finish_deferred_init()
        out[name] = p.data()._data
    return out


def load_params(block, params: Dict[str, jax.Array]):
    """Write arrays back into the block's parameters (post-training)."""
    for name, p in block.collect_params().items():
        if name in params:
            p.set_data(NDArray(params[name]))


def functional_call(block, params: Dict[str, jax.Array], *inputs,
                    training: bool = False, rng=None):
    """Run ``block(*inputs)`` with parameter values taken from ``params``.

    Returns ``(outputs, new_aux)`` where new_aux holds updated aux states
    ({name: array}, empty unless training touches BatchNorm-style state).
    Pure w.r.t. (params, inputs, rng) — safe under jit/grad/shard_map.
    """
    plist = block.collect_params()
    aux_writes = {}
    _TRACE_STACK.append(aux_writes)
    old_rng = _rng.push_trace_key(
        rng if rng is not None else jax.random.key(0))
    try:
        for name, p in plist.items():
            p._trace_data = NDArray(params[name])
        with autograd.pause(train_mode=training):
            with _suspend_hybridization(block):
                out = block(*[NDArray(x) if not isinstance(x, NDArray)
                              else x for x in inputs])
    finally:
        for p in plist.values():
            p._trace_data = None
        _TRACE_STACK.pop()
        _rng.pop_trace_key(old_rng)
    new_aux = {p.name: v._data for p, v in aux_writes.items()}
    if isinstance(out, (list, tuple)):
        raw = type(out)(o._data if isinstance(o, NDArray) else o
                        for o in out)
    else:
        raw = out._data if isinstance(out, NDArray) else out
    return raw, new_aux
