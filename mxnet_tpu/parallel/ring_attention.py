"""Ring attention: sequence/context parallelism over the 'sp' mesh axis.

The reference has no sequence parallelism (SURVEY.md §5.7 — bucketing
only); this is a required TPU-native capability. Design: blockwise
attention with online softmax (the flash-attention recurrence), where K/V
blocks rotate around the ring of 'sp' devices via ``lax.ppermute`` so each
device sees every KV block while holding only its local Q shard —
attention over sequences N× longer than one device's HBM.

Public papers: Ring Attention (Liu et al. 2023), blockwise parallel
attention; implemented here from the recurrence, shard_map-style.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map, pvary as _pvary, \
    SHARD_MAP_KWARGS as _SM_KW

__all__ = ["ring_attention", "sequence_shard"]


def sequence_shard(x, mesh: Mesh, axis_name: str = "sp", seq_dim: int = 2):
    """Place (B, H, T, D) with T sharded over the sp axis."""
    spec = [None] * x.ndim
    spec[seq_dim] = axis_name
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def _online_block(q, k, v, o, m, l, mask=None, scale=1.0):
    """One flash-attention block update: returns (o, m, l) accumulators.
    q:(B,H,Tq,D) k,v:(B,H,Tk,D) o:(B,H,Tq,D) m,l:(B,H,Tq)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (m_new == -inf): exp(-inf - -inf) → nan
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o_new, m_new, l_new


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = False, scale=None):
    """Attention over sequence-sharded q/k/v: (B, H, T_global, D) arrays
    whose T dim is sharded on ``axis_name``. Returns same-sharded output.

    Each ring step computes one local Q×KV block with the online-softmax
    recurrence, then rotates K/V to the next device over ICI (ppermute),
    overlapping compute with the collective (XLA latency-hiding
    scheduler)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    n = mesh.shape[axis_name]

    def local(qb, kb, vb):
        idx = lax.axis_index(axis_name)
        tq = qb.shape[2]
        tk = kb.shape[2]
        o = jnp.zeros(qb.shape[:3] + (vb.shape[-1],), jnp.float32)
        m = jnp.full(qb.shape[:3], -jnp.inf, jnp.float32)
        l = jnp.zeros(qb.shape[:3], jnp.float32)
        # accumulators are device-varying (each sp-rank's differ): annotate
        # so the fori_loop carry type is stable under vma checking
        o, m, l = (_pvary(a, (axis_name,)) for a in (o, m, l))
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(step, carry):
            kb, vb, o, m, l = carry
            # kv block currently held originated at device (idx - step) % n
            src = (idx - step) % n
            if causal:
                q_pos = idx * tq + jnp.arange(tq)[:, None]
                k_pos = src * tk + jnp.arange(tk)[None, :]
                mask = (q_pos >= k_pos)[None, None]
            else:
                mask = None
            o, m, l = _online_block(qb.astype(jnp.float32),
                                    kb.astype(jnp.float32), vb, o, m, l,
                                    mask=mask, scale=scale)
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
            return kb, vb, o, m, l

        kb2, vb2, o, m, l = lax.fori_loop(0, n, body, (kb, vb, o, m, l))
        out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
        return out.astype(q.dtype)

    spec = P(None, None, axis_name, None)
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, **_SM_KW)
    return fn(q, k, v)
