"""mx.viz — network visualization.

Reference: python/mxnet/visualization.py (print_summary:39,
plot_network:214). print_summary walks the Symbol DAG and prints the
layer table with parameter counts; plot_network emits a graphviz
Digraph when the graphviz package is importable (gated — the TPU image
does not ship it).
"""
from __future__ import annotations

__all__ = ["print_summary", "plot_network"]


def _param_count(node, shapes):
    total = 0
    for inp in node._inputs:
        if inp._is_var() and inp._name in shapes and \
                not inp._name.endswith("_label") and inp._name != "data":
            n = 1
            for s in shapes[inp._name]:
                n *= s
            total += n
    return total


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary table (reference:
    visualization.py:39). ``shape``: dict of input shapes for shape
    inference (e.g. {'data': (1, 3, 224, 224)})."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    shapes = {}
    out_shapes = {}
    if shape:
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
        args = symbol.list_arguments()
        shapes = dict(zip(args, arg_shapes))
        shapes.update(zip(symbol.list_auxiliary_states(), aux_shapes))

    cols = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(fields):
        line = ""
        for f, c in zip(fields, cols):
            line = (line + str(f))[:c].ljust(c)
        print(line)

    print("=" * line_length)
    row(header)
    print("=" * line_length)
    total = 0
    for node in symbol._topo():
        if node._is_var():
            continue
        # per-node output shape via eval on the subgraph when available
        oshape = ""
        if shape:
            try:
                _, os_, _ = node.infer_shape(**{
                    k: v for k, v in shape.items()
                    if k in node.list_inputs()})
                oshape = str(os_[0])
            except Exception:
                oshape = "?"
        prev = ",".join(i._name for i in node._inputs
                        if not i._is_var())[:40]
        n_params = _param_count(node, shapes)
        total += n_params
        row([f"{node._name} ({node._op})", oshape, n_params, prev])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz Digraph of the Symbol DAG (reference:
    visualization.py:214). Requires the ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the graphviz package; use "
            "print_summary for a text rendering") from e
    dot = Digraph(name=title, format=save_format)
    for node in symbol._topo():
        if node._is_var():
            if not hide_weights or node._name in ("data",) or \
                    not any(node._name.endswith(s) for s in
                            ("_weight", "_bias", "_gamma", "_beta",
                             "_moving_mean", "_moving_var")):
                dot.node(node._name, node._name, shape="oval")
            continue
        dot.node(node._name, f"{node._name}\n{node._op}", shape="box")
        for inp in node._inputs:
            if inp._is_var() and hide_weights and \
                    any(inp._name.endswith(s) for s in
                        ("_weight", "_bias", "_gamma", "_beta",
                         "_moving_mean", "_moving_var")):
                continue
            dot.edge(inp._name, node._name)
    return dot
