"""Whole-step compilation: ONE donated XLA launch per training step.

The eager training loop pays per-op dispatch three times per step — the
recorded forward, the tape walk of ``backward()`` (one XLA execution per
recorded op), and the optimizer apply (collapsed to one dispatch by
``optimizer.fused`` in the previous round). The reference gets its speed
from compiling the whole computation (Symbol/CachedOp executor), and the
TPU literature is unambiguous that end-to-end step compilation — not
per-op dispatch — is what unlocks MFU ("Automatic Full Compilation of
Julia Programs and ML Models to Cloud TPUs"; the MLPerf TPU-v3 scaling
reports, PAPERS.md). :class:`CompiledTrainStep` closes the remaining gap:

- the user's ``loss_fn`` (arbitrary Python calling gluon blocks — the
  eager ops are trace-transparent) is traced ONCE per input signature;
- the backward comes from ``jax.value_and_grad`` over the parameter
  pytree instead of the tape walk;
- the cross-context gradient reduce and the recorded fused optimizer
  apply (``optimizer.fused`` record/replay, including its value-deduped
  traced-scalar hyperparameter split, so lr/wd/momentum/LossScaler
  rescale never recompile) fold into the same program;
- weights and optimizer slots are donated, so the step updates HBM in
  place and steady-state training is a single device dispatch per step
  with zero host round-trips (one scalar fetch only while float16 loss
  scaling is engaged — the overflow-skip decision is host state).

Batch-tail bucketing: XLA compiles one program per input shape, so the
ragged final batch of an epoch would recompile the whole step. Training
batches are therefore padded up to a power-of-two bucket (the serving
bucketer's pad discipline, ``serving.bucketing``); a mask built from the
traced real-row count zeroes the padded rows' loss so they contribute
exactly ``+0.0`` to every gradient, and the traced row count feeds
``rescale_grad`` so the mean semantics are those of the REAL rows.
``MXNET_TPU_STEP_BUCKETS`` tunes or disables the bucket set. (Batch-
statistics ops — BatchNorm in training mode — see the padded rows; for
those nets a tail batch is shape-stable but not numerically identical
to an unpadded step. See docs/PERFORMANCE.md.)

SPMD mesh mode (``compile_step(mesh=...)`` / ``MXNET_TPU_MESH``): the
same step program partitions over a ``parallel.make_mesh`` device mesh —
weights/optimizer slots placed per ``param_spec`` (replicated by
default, megatron splits via ``parallel.auto_spec``), batches sharded
over ``dp``, and XLA's GSPMD emits the in-program gradient psum. No
per-context Python loop, no host-side allreduce: one donated dispatch
per step at any device count, with the bucket-tail masking and AMP
overflow-skip semantics unchanged under sharding. Evidence rides the
``mxtpu_spmd_*`` series (docs/OBSERVABILITY.md).

Guarded fallback: anything the trace cannot express — sparse gradients,
host-sync/host-state optimizers, data-dependent Python control flow
(detected at trace time), ``grad_req='add'`` accumulation, kvstores
whose reduce is not a plain sum — routes the step through the eager
record/backward path, counted by reason on the shared metrics registry
(``mxtpu_train_step_fallback_total``). ``MXNET_TPU_COMPILED_STEP=0``
disables the compiled path globally.
"""
from __future__ import annotations

import os
import threading
import warnings

import numpy as _np

__all__ = ["CompiledTrainStep", "step_buckets_config", "pick_train_bucket",
           "pad_rows"]

# trace-time fallback reasons that are deterministic for this trainer /
# loss_fn — retrying them every step would re-pay a failed trace
_STICKY_REASONS = ("trace_failed", "unrecordable", "state_leaf",
                   "exec_failed", "mesh_multictx")


class _Fallback(Exception):
    """Raised when the step cannot be compiled; carries the reason."""

    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


class _TraceFrame(dict):
    """Trace-capture frame pushed on ``parameter._TRACE_STACK``: dict of
    parameter writes (``Parameter -> traced NDArray``, the contract
    CachedOp's aux frame established) plus the set of parameters READ
    with concrete (non-input) values — those get promoted to program
    inputs on the rebuild pass instead of baking stale constants."""

    __slots__ = ("reads",)

    def __init__(self):
        super().__init__()
        self.reads = set()


def step_buckets_config(override=None):
    """Resolve the training bucket policy: ``None`` = bucketing off
    (exact shapes; ragged tails recompile), ``"auto"`` = powers of two
    up to the largest batch seen, or an explicit sorted list of sizes.
    ``override`` (the ``buckets=`` argument) wins over the
    ``MXNET_TPU_STEP_BUCKETS`` env: False/0 = off, a list = explicit."""
    if override is not None:
        if override is False or override == 0:
            return None
        if override is True or override == "auto":
            return "auto"
        return sorted(int(b) for b in override)
    v = os.environ.get("MXNET_TPU_STEP_BUCKETS", "1").strip().lower()
    if v in ("0", "off", "false", "none"):
        return None
    if v in ("1", "auto", "on", ""):
        return "auto"
    return sorted(int(t) for t in v.split(","))


def pick_train_bucket(n, buckets, max_batch):
    """Bucket for a batch of ``n`` rows under a policy resolved by
    :func:`step_buckets_config` — the ONE training bucket policy,
    shared by :class:`CompiledTrainStep` and ``parallel.ShardedTrainer``
    (which rounds the result up to its mesh's dp extent)."""
    from .serving.bucketing import bucket_sizes, pick_bucket
    if buckets is None:
        return n
    if buckets == "auto":
        return pick_bucket(n, bucket_sizes(max_batch))
    return pick_bucket(n, buckets) if n <= buckets[-1] else n


def pad_rows(v, bucket):
    """Zero-pad ``v`` (array or NDArray, batch on axis 0) up to
    ``bucket`` rows; returns ``v`` itself when already full. Host
    arrays pad through the serving bucketer, device arrays with one
    concatenate — the single pad discipline for every training path."""
    import jax.numpy as jnp
    from .ndarray import NDArray
    from .serving.bucketing import pad_batch
    arr = v._data if isinstance(v, NDArray) else v
    n = arr.shape[0]
    if n == bucket:
        return v
    if isinstance(arr, _np.ndarray):
        return pad_batch(arr, bucket)
    return jnp.concatenate(
        [arr, jnp.zeros((bucket - n,) + tuple(arr.shape[1:]), arr.dtype)],
        axis=0)


def _tracer():
    """The process tracer (lazy import keeps `import mxnet_tpu` light;
    get_tracer itself is one lock-free global read after first use)."""
    from .observability.tracing import get_tracer
    return get_tracer()


def _metrics():
    from .observability import get_registry
    reg = get_registry()
    return {
        "dispatch": reg.counter(
            "mxtpu_train_step_dispatch_total",
            "Compiled whole-step program launches (steady state: exactly "
            "1 per training step)."),
        "compiled": reg.counter(
            "mxtpu_train_step_compiled_total",
            "Training steps executed as one compiled forward+backward+"
            "reduce+update program."),
        "fallback": reg.counter(
            "mxtpu_train_step_fallback_total",
            "Training steps that fell back to the eager record/backward "
            "path, by reason.", ("reason",)),
        "bucket_compiles": reg.counter(
            "mxtpu_train_step_bucket_compiles_total",
            "Whole-step program builds, by batch bucket (flat after "
            "warmup = zero steady-state recompiles).", ("bucket",)),
        "padded_rows": reg.counter(
            "mxtpu_train_step_padded_rows_total",
            "Zero rows added to ragged batch tails to hit a pre-compiled "
            "bucket (the FLOP cost of never recompiling)."),
    }


class CompiledTrainStep:
    """One buffer-donating XLA program per (structure, bucketed shape,
    dtype) covering forward + loss + backward + cross-context gradient
    reduce + optimizer update. Build via
    ``gluon.Trainer.compile_step(loss_fn)``.

    ``loss_fn(*batch)`` is arbitrary Python calling the net through the
    eager API; it must return the per-sample loss (any shape with the
    batch on axis 0, or a scalar), or a tuple whose FIRST element is the
    loss — the remaining elements (predictions etc.) ride along as
    program outputs. Calling the step returns exactly what ``loss_fn``
    returned, with padded rows sliced off.

    Semantics mirror ``loss.backward(); trainer.step(batch_rows)``: the
    gradient is of the loss SUM (a backward seeded with ones) and the
    optimizer's ``rescale_grad`` divides by the real row count. BN aux
    states (running stats) update inside the program. ``param.grad()``
    buffers are NOT written — readers of raw gradients belong on the
    eager path (``MXNET_TPU_COMPILED_STEP=0``).
    """

    # consecutive dispatch failures tolerated before the compiled path is
    # disabled for this step object (trace failures disable immediately)
    MAX_EXEC_FAILURES = 3

    def __init__(self, trainer, loss_fn, buckets=None, donate=True,
                 remat=None, mesh=None, param_spec=None):
        if remat not in (None, "", "full", "dots"):
            raise ValueError(
                f"remat must be None, 'full' or 'dots', got {remat!r}")
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._donate = donate
        self._remat = remat or None
        if isinstance(mesh, str):
            from .parallel.mesh import parse_mesh
            mesh = parse_mesh(mesh)
        # SPMD mesh mode: every program input is placed onto `mesh` as
        # one global array (weights/slots per `param_spec`, batches
        # dp-sharded) and the SAME step program partitions over it —
        # XLA's GSPMD emits the in-program gradient psum, so multi-chip
        # training keeps the 1-dispatch/zero-host-round-trip contract.
        self._mesh = mesh
        self._param_spec = param_spec
        self._wspecs = {}        # param name -> clamped PartitionSpec
        self._buckets = step_buckets_config(buckets)
        self._max_batch = 0
        # mid-run resume: a trainer restored from a checkpoint carries
        # the saved run's bucket warmth — seed it so resumed tails pad
        # to the same buckets (identical numerics, no cold recompiles);
        # registration lets a restore_state() AFTER compile_step reach
        # live step objects the same way
        registry = getattr(trainer, "_compiled_steps", None)
        if registry is not None:
            registry.add(self)
        restored = getattr(trainer, "_restored_step_state", None) or {}
        self.seed_bucket_state(restored.get("max_batch", 0))
        self._cache = {}      # signature key -> (compiled, meta)
        self._disabled = None
        self._exec_failures = 0
        self._obs = None
        self._lock = threading.Lock()
        self.last_reason = None      # fallback reason of the last call
        self.last_cost_analysis = None

    # ------------------------------------------------------ eligibility --
    def _why_ineligible(self):
        """None when this call can take the compiled path, else the
        fallback-reason label (host-sync optimizers, sparse grads,
        non-foldable kvstores, gradient accumulation, env gate)."""
        if os.environ.get("MXNET_TPU_COMPILED_STEP", "1") == "0":
            return "env_disabled"
        if self._disabled is not None:
            return self._disabled
        tr = self._trainer
        from .optimizer.fused import fusable
        if tr._update_on_kvstore:
            return "kvstore"
        if tr._kvstore is not None and not getattr(
                tr._kvstore, "fused_reduce_compatible", False):
            return "kvstore"
        if not fusable(tr._optimizer):
            return "optimizer"
        for p in tr._params:
            if p.grad_req == "add":
                return "grad_req_add"
            if p.grad_req != "null" and (p.stype == "row_sparse"
                                         or p.grad_stype == "row_sparse"):
                return "sparse_grad"
        if self._mesh is not None:
            # the mesh IS the multi-device story: per-context replicas
            # and a device mesh are two incompatible placements for the
            # same weight — a trainer built over replicated contexts
            # keeps the replica path (deterministic for this trainer,
            # hence sticky)
            if any(p._data is not None and len(p._data) > 1
                   for p in tr._params):
                return "mesh_multictx"
        return None

    def _obs_metrics(self):
        if self._obs is None:
            self._obs = _metrics()
        return self._obs

    # -------------------------------------------------------- bucketing --
    def seed_bucket_state(self, max_batch):
        """Adopt bucket warmth from a restored checkpoint (monotonic —
        never shrinks what this step already saw)."""
        self._max_batch = max(self._max_batch, int(max_batch or 0))

    def _pick_bucket(self, n):
        if self._buckets == "auto":
            self._max_batch = max(self._max_batch, n)
        bucket = pick_train_bucket(n, self._buckets, self._max_batch)
        if self._mesh is not None and self._buckets is not None:
            # keep the batch axis evenly shardable over dp; the extra
            # pad rows are masked like any other tail padding. (With
            # bucketing off the batch stays unpadded — an indivisible
            # batch then simply replicates, still correct SPMD.)
            from .parallel.mesh import round_up_to_dp
            bucket = round_up_to_dp(bucket, self._mesh)
        return bucket

    # ------------------------------------------------------------- call --
    def __call__(self, *args):
        import time as _time
        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        obs = self._obs_metrics()
        t0 = _time.monotonic()
        # the step span (no kwargs, no attrs: the disabled path must
        # allocate nothing per step); trace/compile/dispatch/fallback
        # appear as children via contextvar nesting
        with _tracer().span("mxtpu.train_step", "step", None, None,
                            tr._step_count):
            reason = self._why_ineligible()
            if reason is not None:
                return self._eager_step(args, reason)
            try:
                return self._compiled_step(args, obs, t0)
            except _Fallback as e:
                if e.reason == "scalar_loss_bucketed":
                    # a pre-reduced loss cannot be pad-corrected: drop
                    # the bucketing (exact shapes still compile whole-
                    # step) and retry once
                    self._buckets = None
                    try:
                        return self._compiled_step(args, obs, t0)
                    except _Fallback as e2:
                        e = e2
                if e.reason in _STICKY_REASONS:
                    self._disabled = e.reason
                return self._eager_step(args, e.reason)

    # ---------------------------------------------------- the fast path --
    def _compiled_step(self, args, obs, t0):
        import time as _time
        import jax
        from . import _rng
        from .gluon.block import _flatten_arrays, _flat_flags
        from .optimizer import fused as _fused

        tr = self._trainer
        opt, upd = tr._optimizer, tr._updaters[0]
        scaler = getattr(tr, "_amp_loss_scaler", None)
        engaged = scaler is not None and scaler.loss_scale != 1.0

        flat_in, in_fmt = _flatten_arrays(args)
        flags = _flat_flags(in_fmt)
        arrays = [v for v, f in zip(flat_in, flags) if f]
        opaque = tuple(v for v, f in zip(flat_in, flags) if not f)
        if not arrays or getattr(arrays[0], "ndim", 0) == 0:
            raise _Fallback("no_batch_axis")
        n = int(arrays[0].shape[0])

        # deferred parameter shapes resolve through one eager predict
        # pass (no aux writes — CachedOp's warm-up discipline)
        if any(p._data is None for p in tr._params):
            from . import autograd
            with autograd.pause(train_mode=False):
                self._loss_fn(*args)

        work = [(i, p) for i, p in enumerate(tr._params)
                if p.grad_req != "null" and p._data is not None]
        if not work:
            raise _Fallback("no_trainable")
        bucket = self._pick_bucket(n)

        # ---- phase A: record the optimizer apply on host ----------------
        # All host bookkeeping (update counts, schedulers, Adam bias
        # correction, AMP rescale) advances exactly as in the eager loop;
        # a fallback from here on must roll the counts back.
        scale = tr._scale / (scaler.loss_scale if engaged else 1.0)
        opt.rescale_grad = scale / n
        _fused.prepare_states(opt, upd, work)
        try:
            roles, weight_nds, grad_nds, state_nds, state_defs = \
                _fused.build_roles(upd, work)
        except ValueError:
            raise _Fallback("state_leaf") from None
        rec = _fused.record_program(upd, work, grad_nds, weight_nds, roles)
        if not rec.ok:
            _fused.rollback_counts(opt, work)
            raise _Fallback("unrecordable")

        mesh_pin = None
        if self._mesh is not None:
            # place weights + optimizer slots onto the mesh as global
            # arrays (no-op once placed: donation hands back outputs
            # with the same, constraint-pinned shardings)
            mesh_pin = self._place_mesh(work, weight_nds, state_nds,
                                        state_defs)

        nts = [p for p in tr._params
               if p.grad_req == "null" and p._data is not None]
        key = (in_fmt, opaque, bucket, engaged, self._mesh,
               self._buckets is not None, type(opt), tuple(rec.program),
               tuple(state_defs),
               tuple((tuple(a.shape[1:]) if a.shape[:1] == (n,)
                      else ("F",) + tuple(a.shape),
                      str(_np.dtype(_dtype_of(a)))) for a in arrays),
               tuple((tuple(w.shape), str(w.dtype)) for w in weight_nds),
               tuple((tuple(s.shape), str(s.dtype)) for s in state_nds))
        try:
            hash(key)
        except TypeError:
            _fused.rollback_counts(opt, work)
            raise _Fallback("unhashable_signature") from None

        batch_vals = self._stage_batch(arrays, n, bucket)
        if self._mesh is not None:
            batch_vals = self._place_batch(batch_vals, bucket)
        weights = [w._data for w in weight_nds]
        states = [s._data for s in state_nds]
        scalars = tuple(rec.slot_values)
        ls = float(scaler.loss_scale) if engaged else 1.0
        rng_base = _rng.base_key()
        rng_draw = _rng.reserve_draw()

        entry = self._cache.get(key)
        if entry is None:
            with self._lock:
                entry = self._cache.get(key)
                if entry is None:
                    with _tracer().span("mxtpu.train_step.compile",
                                        "step") as _sp:
                        _sp.set("bucket", bucket)
                        try:
                            entry = self._build(
                                rec.program, work, nts, in_fmt, flags,
                                opaque, bucket, engaged,
                                (weights, states, scalars, ls, n,
                                 rng_base, rng_draw, batch_vals),
                                mesh_pin=mesh_pin)
                        except _Fallback:
                            _fused.rollback_counts(opt, work)
                            raise
                    self._cache[key] = entry
                    obs["bucket_compiles"].labels(bucket=str(bucket)).inc()
                    if self._mesh is not None:
                        from .parallel.mesh import note_mesh, spmd_metrics
                        sobs = spmd_metrics()
                        sobs["programs"].labels(
                            devices=str(self._mesh.devices.size),
                            bucket=str(bucket)).inc()
                        note_mesh(self._mesh)
        compiled, meta = entry

        nt_all = meta["nt_params"]
        if self._mesh is not None:
            nt_vals = self._place_nt(nt_all)
        else:
            nt_vals = [p._get_primary()._data for p in nt_all]
        try:
            with _tracer().span("mxtpu.train_step.dispatch", "step"):
                outs = compiled(weights, nt_vals, states, scalars, ls, n,
                                rng_base, rng_draw, batch_vals)
        except Exception:
            if any(w.is_deleted() for w in weights) or \
                    any(s.is_deleted() for s in states):
                raise       # donation consumed the inputs: nothing to
                            # fall back onto — surface the real failure
            warnings.warn("compiled train step failed; falling back to "
                          "the eager record/backward path", stacklevel=4)
            with self._lock:
                self._cache.pop(key, None)
            self._exec_failures += 1
            reason = "exec_failed" if \
                self._exec_failures >= self.MAX_EXEC_FAILURES else \
                "exec_retry"
            _fused.rollback_counts(opt, work)
            raise _Fallback(reason) from None
        self._exec_failures = 0
        new_w, new_s, aux_out, loss_out, extras, flag = outs

        overflow = False
        if engaged:
            overflow = not bool(_np.asarray(flag))  # the ONE host sync
        if overflow:
            # the program kept the pre-step weights/slots (in-program
            # where()); mirror the eager amp_step skip exactly: no count
            # advance, no step tick, scale halves
            _fused.rollback_counts(opt, work)
            scaler.update_scale(overflow=True)
            warnings.warn(
                f"AMP: gradient overflow, skipping update and reducing "
                f"loss scale to {scaler.loss_scale}", stacklevel=3)
        else:
            if engaged:
                scaler.update_scale(overflow=False)
            tr._step_count += 1

        for k, (i, param) in enumerate(work):
            replicas = param.list_data()
            replicas[0]._data = new_w[k]
            for other in replicas[1:]:
                other._data = jax.device_put(new_w[k],
                                             other.context.jax_device)
        for leaf, data in zip(state_nds, new_s):
            leaf._data = data
        for p, v in zip(meta["aux_params"], aux_out):
            ctxs = list(p._data)
            p._data[ctxs[0]]._data = v
            for c in ctxs[1:]:
                p._data[c]._data = jax.device_put(v, c.jax_device)

        obs["dispatch"].inc()
        obs["compiled"].inc()
        if self._mesh is not None:
            from .parallel.mesh import spmd_metrics
            sobs = spmd_metrics()
            sobs["dispatch"].inc()
            if meta.get("spmd_grad_bytes"):
                sobs["bytes"].labels(collective="grad_reduce").inc(
                    meta["spmd_grad_bytes"])
        if bucket != n:
            obs["padded_rows"].inc(bucket - n)
        tobs = tr._obs_metrics()
        if not overflow:
            # an overflow-skip records nothing, mirroring the eager
            # amp_step early return — secs samples stay 1:1 with steps
            tobs["secs"].observe(_time.monotonic() - t0)
            tobs["steps"].inc()
            tobs["examples"].inc(n)
            from .resilience import faults
            from .resilience import async_writer as _aw
            _aw.note_step_overlap()
            faults.on_step(tr._step_count)
        self.last_reason = None
        return self._package(meta, loss_out, extras, n, bucket)

    # --------------------------------------------------- mesh placement --
    def _leaf_spec_of(self, name, shape):
        """Clamped PartitionSpec for a named parameter on this mesh
        (cached: the clamp result is a pure function of name+shape)."""
        from jax.sharding import PartitionSpec
        from .parallel.mesh import leaf_spec
        spec = self._wspecs.get(name)
        if spec is None:
            raw = self._param_spec(name, tuple(shape)) \
                if self._param_spec else PartitionSpec()
            spec = leaf_spec(raw, tuple(shape), self._mesh)
            self._wspecs[name] = spec
        return spec

    def _place_mesh(self, work, weight_nds, state_nds, state_defs):
        """Move weights and optimizer slots onto the mesh (no-op when
        already placed). Slots ride their parameter's spec when
        weight-shaped, else replicate. Returns (wspecs, sspecs) aligned
        with weight_nds/state_nds for the build's output pinning."""
        from .parallel.mesh import leaf_spec, place_global
        mesh = self._mesh
        wspecs, sspecs = [], []
        si = 0
        for k, (i, param) in enumerate(work):
            w = weight_nds[k]
            spec = self._leaf_spec_of(param.name, w.shape)
            wspecs.append(spec)
            w._data = place_global(w._data, mesh, spec)
            for _ in range(state_defs[k].num_leaves):
                leaf = state_nds[si]
                lspec = leaf_spec(spec, tuple(leaf.shape), mesh)
                leaf._data = place_global(leaf._data, mesh, lspec)
                sspecs.append(lspec)
                si += 1
        return wspecs, sspecs

    def _place_nt(self, nt_params):
        """Place non-trainable program inputs (frozen weights, BN
        stats) on the mesh; returns their values in input order."""
        from .parallel.mesh import place_global
        vals = []
        for p in nt_params:
            nd = p._get_primary()
            spec = self._leaf_spec_of(p.name, nd.shape)
            nd._data = place_global(nd._data, self._mesh, spec)
            vals.append(nd._data)
        return vals

    def _place_batch(self, batch_vals, bucket):
        """Shard padded batch inputs over dp (replicate anything not
        batch-major). Multi-process meshes treat each process's value as
        its local shard of the global batch — the dist_sync layout."""
        from jax.sharding import PartitionSpec
        from .parallel.mesh import batch_spec, place_global
        mesh = self._mesh
        out = []
        for v in batch_vals:
            nd = getattr(v, "ndim", 0)
            if nd and getattr(v, "shape", ())[:1] == (bucket,):
                spec = batch_spec(nd, mesh, bucket)
            else:
                spec = PartitionSpec()
            host_has = "local_shard" if len(spec) else "full"
            out.append(place_global(v, mesh, spec, host_has=host_has))
        return out

    # ------------------------------------------------------------ build --
    def _build(self, program, work, nts, in_fmt, flags, opaque, bucket,
               engaged, sample_inputs, mesh_pin=None):
        """Trace + AOT-compile the whole-step program for one signature.
        Two passes: the first lowering discovers parameters the loss
        reads or writes outside the Trainer's set; those are promoted to
        program inputs and the step re-lowered, so e.g. frozen-backbone
        BN stats never bake stale constants. AOT (lower/compile) instead
        of plain jit so the executable's cost_analysis feeds bench MFU.
        Returns (compiled, meta)."""
        import jax
        from .optimizer.fused import bind_entries
        entries = bind_entries(program)
        trainables = [p for _, p in work]
        w, s, sc, ls, n, rb, rd, bv = sample_inputs
        extra = []
        for attempt in (0, 1):
            nt_all = nts + extra
            meta = {"nt_params": nt_all, "aux_params": None,
                    "single": True, "loss_scalar": False,
                    "reads": set(), "writes": set()}
            if self._mesh is not None:
                dp = dict(self._mesh.shape).get("dp", 1)
                if dp > 1 and bucket and bucket % dp == 0:
                    # logical per-step gradient-psum payload over dp:
                    # one grad the size of every trainable weight
                    meta["spmd_grad_bytes"] = sum(
                        int(a.size) * a.dtype.itemsize for a in w)
            fn = self._make_fn(entries, trainables, nt_all, in_fmt, flags,
                               opaque, bucket, engaged, meta,
                               mesh_pin=mesh_pin)
            jitted = jax.jit(fn, donate_argnums=(0, 2) if self._donate
                             else ())
            nt_vals = self._place_nt(nt_all) if self._mesh is not None \
                else [p._get_primary()._data for p in nt_all]
            try:
                lowered = jitted.lower(w, nt_vals, s, sc, ls, n, rb, rd,
                                       bv)
            except _Fallback:
                raise
            except Exception as e:
                # data-dependent Python control flow, host syncs inside
                # the loss, structures the trace cannot carry —
                # deterministic for this signature
                warnings.warn(
                    "whole-step trace failed "
                    f"({type(e).__name__}: {e}); training continues on "
                    "the eager path", stacklevel=5)
                raise _Fallback("trace_failed") from None
            if meta["loss_scalar"] and self._buckets is not None:
                raise _Fallback("scalar_loss_bucketed")
            discovered = sorted(
                (meta["reads"] | meta["writes"]) - set(trainables)
                - set(nt_all),
                key=lambda p: p.name)
            discovered = [p for p in discovered if p._data is not None]
            if discovered and attempt == 0:
                extra = extra + discovered
                continue
            if discovered:
                raise _Fallback("trace_failed")  # nondeterministic trace
            break
        try:
            compiled = lowered.compile()
        except Exception as e:
            # backend compile failure (XLA OOM, lost tunnel): the caller
            # must see a _Fallback so phase-A counters roll back and the
            # step still runs eagerly. Counts against the same breaker as
            # execution failures — a deterministic compile failure would
            # otherwise re-pay the full trace+compile every step forever.
            warnings.warn(
                f"whole-step compile failed ({type(e).__name__}: {e}); "
                "training continues on the eager path", stacklevel=4)
            self._exec_failures += 1
            reason = "exec_failed" if \
                self._exec_failures >= self.MAX_EXEC_FAILURES else \
                "exec_retry"
            raise _Fallback(reason) from None
        try:
            cost = compiled.cost_analysis()
            self.last_cost_analysis = (cost[0] if isinstance(
                cost, (list, tuple)) else cost)
        except Exception:
            pass
        return compiled, meta

    def _make_fn(self, entries, trainables, nts, in_fmt, flags, opaque,
                 bucket, engaged, meta, mesh_pin=None):
        import jax
        import jax.numpy as jnp
        if mesh_pin is not None:
            # pin the updated weights/slots to their input shardings so
            # XLA aliases the donated buffers and the next step's
            # placement check is a no-op — without the constraint GSPMD
            # may propagate a different output layout and every step
            # would pay a reshard
            from jax.sharding import NamedSharding
            _w_sh = [NamedSharding(self._mesh, s) for s in mesh_pin[0]]
            _s_sh = [NamedSharding(self._mesh, s) for s in mesh_pin[1]]
        from . import _rng, autograd
        from .gluon.block import _regroup
        from .gluon.parameter import _TRACE_STACK
        from .ndarray import NDArray
        from .optimizer.fused import apply_entries
        loss_fn = self._loss_fn
        masked = self._buckets is not None
        remat = self._remat

        def run_loss(ws, nt_vals, xvals, key, mask):
            """One forward+loss over (a slice of) the batch; returns
            (differentiable head, (loss value, extras, writes))."""
            frame = _TraceFrame()
            _TRACE_STACK.append(frame)
            old = _rng.push_trace_key(key)
            touched = []
            try:
                for p, v in zip(trainables, ws):
                    p._trace_data = NDArray(v)
                    touched.append(p)
                for p, v in zip(nts, nt_vals):
                    p._trace_data = NDArray(v)
                    touched.append(p)
                merged, ai, oi = [], 0, 0
                for is_arr in flags:
                    if is_arr:
                        merged.append(NDArray(xvals[ai]))
                        ai += 1
                    else:
                        merged.append(opaque[oi])
                        oi += 1
                with autograd.pause(train_mode=True):
                    out = loss_fn(*_regroup(merged, in_fmt))
            finally:
                for p in touched:
                    p._trace_data = None
                for p in frame:
                    p._trace_data = None
                _TRACE_STACK.pop()
                _rng.pop_trace_key(old)
            single = not isinstance(out, tuple)
            outs = (out,) if single else tuple(out)
            loss = outs[0]
            extras = tuple(o._data if isinstance(o, NDArray) else o
                           for o in outs[1:])
            meta["single"] = single
            meta["reads"] |= frame.reads
            meta["writes"] |= set(frame)
            # aux writes flow out as a name-ordered TUPLE (a Parameter-
            # keyed dict would need sortable pytree keys); the order is
            # pinned on meta during the (deterministic) trace
            worder = sorted(frame, key=lambda p: p.name)
            meta["aux_params"] = worder
            wvals = tuple(
                frame[p]._data if isinstance(frame[p], NDArray)
                else frame[p] for p in worder)
            lv = loss._data if isinstance(loss, NDArray) \
                else jnp.asarray(loss)
            if lv.ndim == 0:
                meta["loss_scalar"] = True
                head = lv
            elif mask is not None:
                factor = mask.reshape(
                    mask.shape + (1,) * (lv.ndim - 1)).astype(lv.dtype)
                head = (lv * factor).sum()
            else:
                # the eager gradient seed is ones == grad of the SUM
                head = lv.sum()
            return head, (lv, extras, wvals)

        def step_fn(weights, nt_vals, states, scalars, loss_scale, n_real,
                    rng_base, rng_draw, xvals):
            key = jax.random.fold_in(rng_base, rng_draw)
            mask = (jnp.arange(bucket) < n_real) if masked else None

            def head_of(h):
                # with scaling engaged the eager head is loss*loss_scale;
                # scaling the summed head by the traced scale produces
                # cotangents that match element-for-element
                return h * loss_scale if engaged else h

            # one forward over the whole batch on the primary context —
            # per-context gradient partials never materialize, so the
            # cross-context reduce is subsumed (the updated weights are
            # broadcast to every replica after the dispatch)
            def objective(ws):
                h, aux = run_loss(ws, nt_vals, xvals, key, mask)
                return head_of(h), aux
            if remat == "full":
                objective = jax.checkpoint(objective)
            elif remat == "dots":
                objective = jax.checkpoint(
                    objective,
                    policy=jax.checkpoint_policies.dots_saveable)
            (_, (loss_v, extras, aux_vals)), grads = jax.value_and_grad(
                objective, has_aux=True)(list(weights))

            bufs = {}
            for k, w in enumerate(weights):
                bufs[("w", k)] = w
            for k, g in enumerate(grads):
                bufs[("g", k)] = g
            for j, st in enumerate(states):
                bufs[("s", j)] = st
            flag = jnp.asarray(True)
            if engaged:
                fin = [jnp.isfinite(g).all() for g in grads]
                flag = jnp.all(jnp.stack(fin)) if fin else flag
            apply_entries(entries, bufs, scalars)
            new_w = [bufs[("w", k)] for k in range(len(weights))]
            new_s = [bufs[("s", j)] for j in range(len(states))]
            if engaged:
                # overflow => keep the pre-step weights and slots (the
                # eager amp_step update skip, decided in-program)
                new_w = [jnp.where(flag, nw, ow)
                         for nw, ow in zip(new_w, weights)]
                new_s = [jnp.where(flag, ns, os_)
                         for ns, os_ in zip(new_s, states)]
            if mesh_pin is not None:
                new_w = [jax.lax.with_sharding_constraint(v, sh)
                         for v, sh in zip(new_w, _w_sh)]
                new_s = [jax.lax.with_sharding_constraint(v, sh)
                         for v, sh in zip(new_s, _s_sh)]
            return new_w, new_s, aux_vals, loss_v, extras, flag

        return step_fn

    # --------------------------------------------------------- plumbing --
    def _stage_batch(self, arrays, n, bucket):
        """Padded program-input values. Only arrays whose leading axis is
        the batch axis are padded; host arrays pad on host, device arrays
        with one tiny concatenate (ragged tails only — full buckets copy
        nothing). The pad-row metric is charged by the CALLER after the
        padded program actually dispatched (a step that falls back runs
        unpadded)."""
        from .ndarray import NDArray
        out = []
        for a in arrays:
            v = a._data if isinstance(a, NDArray) else a
            if hasattr(v, "shape") and v.shape[:1] == (n,) and bucket != n:
                v = pad_rows(v, bucket)
            out.append(v)
        return out

    def _package(self, meta, loss_out, extras, n, bucket):
        from .ndarray import NDArray

        def trim(v):
            if hasattr(v, "shape") and v.shape[:1] == (bucket,) \
                    and n != bucket:
                v = v[:n]
            return NDArray(v)
        loss = trim(loss_out)
        if meta["single"]:
            return loss
        return (loss,) + tuple(trim(e) for e in extras)

    # ------------------------------------------------------- eager path --
    def _eager_step(self, args, reason):
        """The guarded fallback: the plain record/backward/step loop
        (which itself runs the fused one-dispatch update when it can).
        Counted by reason; semantics identical to hand-written eager
        training, including the AMP wrapper's overflow skip."""
        from . import autograd
        from .gluon.block import _flatten_arrays, _flat_flags
        obs = self._obs_metrics()
        obs["fallback"].labels(reason=reason).inc()
        self.last_reason = reason
        tr = self._trainer
        scaler = getattr(tr, "_amp_loss_scaler", None)
        flat_in, fmt = _flatten_arrays(args)
        n = 1
        for v, f in zip(flat_in, _flat_flags(fmt)):
            if f and getattr(v, "ndim", 0):
                n = int(v.shape[0])
                break
        with _tracer().span("mxtpu.train_step.fallback", "step") as sp:
            sp.set("reason", reason)
            with autograd.record():
                out = self._loss_fn(*args)
                loss = out[0] if isinstance(out, tuple) else out
                head = loss * scaler.loss_scale \
                    if scaler is not None and scaler.loss_scale != 1.0 \
                    else loss
            head.backward()
            tr.step(n)
        return out

    # ------------------------------------------------------- introspect --
    def cache_size(self):
        return len(self._cache)

    def cost_analysis(self):
        """XLA cost analysis of the most recently built step program
        (None before the first compile) — feeds bench.py's MFU."""
        return self.last_cost_analysis


def _dtype_of(a):
    d = getattr(a, "dtype", None)
    return d if d is not None else _np.asarray(a).dtype
