"""mx.npx — operator extensions beyond the NumPy standard.

Reference: python/mxnet/numpy_extension/__init__.py. Carries (a) the
numpy-semantics switches (set_np family, re-exported from util), (b) the
framework op surface that stock NumPy has no name for (convolution,
batch_norm, softmax, embedding, pooling, sequence ops, ...), generated
from the op registry with np-ndarray outputs, and (c) device/session
helpers (cpu/gpu/num_gpus/waitall/seed).
"""
from __future__ import annotations

import functools

from ..util import (set_np, reset_np, set_np_shape, set_np_array,
                    is_np_shape, is_np_array, is_np_default_dtype,
                    set_np_default_dtype, np_shape, np_array, use_np,
                    use_np_shape, use_np_array)
from ..context import cpu, gpu, tpu, num_gpus, num_tpus, current_context
from ..ops.registry import _REGISTRY
from ..ndarray.register import make_op_func
from ..numpy.multiarray import to_np, ndarray
from ..numpy import random as _np_random
from .. import _rng

__all__ = ["set_np", "reset_np", "set_np_shape", "set_np_array",
           "is_np_shape", "is_np_array", "is_np_default_dtype",
           "set_np_default_dtype", "np_shape", "np_array", "use_np",
           "use_np_shape", "use_np_array", "cpu", "gpu", "tpu",
           "num_gpus", "num_tpus", "current_context", "current_device",
           "seed", "waitall", "save", "load"]

current_device = current_context


def seed(seed_state):
    _rng.seed(seed_state)


def waitall():
    from .. import ndarray as _nd
    _nd.waitall()


def save(file, arr):
    from .. import numpy as _np_mod
    _np_mod.save(file, arr)


def load(file):
    from .. import numpy as _np_mod
    return _np_mod.load(file)


def _npx_func(opfn):
    @functools.wraps(opfn)
    def fn(*args, **kwargs):
        return to_np(opfn(*args, **kwargs))
    return fn


from .dynamic import (dynamic_shape_bound,  # noqa: F401,E402
                      current_shape_bound, shape_bucket)
__all__ += ["dynamic_shape_bound", "current_shape_bound", "shape_bucket"]

# Generate the op surface from the registry (the same source that feeds
# mx.nd), wrapped to return mx.np ndarrays. Internal/underscore ops are
# omitted, matching the reference's public npx namespace.
for _name, _op in list(_REGISTRY.items()):
    if _name.startswith("_") or _name in globals():
        continue
    globals()[_name] = _npx_func(make_op_func(_op))
    __all__.append(_name)

del _name, _op
