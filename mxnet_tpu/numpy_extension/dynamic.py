"""Bounded-shape execution of dynamic-output ops under jit.

The reference re-infers shapes in-executor at runtime for ops whose
output shape depends on VALUES (np.unique, boolean_mask, nonzero —
reference: src/executor/graph_executor.cc:1497-1530 runtime shape
re-inference). XLA compiles static shapes, so the TPU-native strategy
(SURVEY §7) is *bounded shapes + bucketed recompilation*:

- inside ``dynamic_shape_bound(n)``, dynamic-output ops produce
  fixed-size results padded to ``n`` (jnp's ``size=``/``fill_value=``
  contract), making them jit-compatible;
- callers that see many different run-time cardinalities round the
  bound up with :func:`shape_bucket` so the number of distinct compiled
  programs stays logarithmic, not linear, in the observed sizes.

Example::

    from mxnet_tpu import np as mnp, npx

    @jax.jit
    def f(x):
        with npx.dynamic_shape_bound(8):
            u = mnp.unique(x)              # shape (8,), padded
            nz = mnp.nonzero(x)[0]         # shape (8,), padded
        return u, nz

Without an active bound (and no explicit ``size=``), these ops remain
eager-only exactly like before — tracing them raises jax's concretization
error, which is the honest failure mode.

CACHING CAVEAT: the bound is consumed at TRACE time and is NOT part of
jit's cache key. Enter the context INSIDE the jitted function (as above)
so the traced program and the bound always agree; wrapping a call to an
already-jitted function in a *different* bound is a cache hit on the old
program and would silently keep the old size. If the bound must vary,
make it an explicit ``size=``/static argument (see
tests/test_dynamic_shapes.py::test_shape_bucket_bounds_recompiles).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = ["dynamic_shape_bound", "current_shape_bound", "shape_bucket"]

_STATE = threading.local()


def current_shape_bound():
    """The innermost active bound, or None."""
    stack = getattr(_STATE, "stack", None)
    return stack[-1] if stack else None


@contextmanager
def dynamic_shape_bound(n: int):
    """Within this context, dynamic-output ops (np.unique, np.nonzero,
    np.flatnonzero, np.argwhere, npx/contrib boolean_mask) emit
    fixed-size outputs padded to ``n`` and are therefore traceable."""
    if n <= 0:
        raise ValueError(f"bound must be positive, got {n}")
    stack = getattr(_STATE, "stack", None)
    if stack is None:
        stack = _STATE.stack = []
    stack.append(int(n))
    try:
        yield
    finally:
        stack.pop()


def shape_bucket(n: int, base: int = 2, minimum: int = 8) -> int:
    """Round a run-time cardinality up to a bucket boundary (powers of
    ``base``), bounding how many distinct XLA programs a varying-size
    workload compiles — the recompilation half of the strategy."""
    b = minimum
    while b < n:
        b *= base
    return b
