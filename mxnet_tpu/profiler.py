"""mx.profiler — profiling bridge over jax.profiler.

Reference surface: python/mxnet/profiler.py (set_config :40, set_state
:115, pause/resume :146/:160, dump :173, dumps :194 aggregate stats,
scope/annotations) backed by src/profiler/profiler.h:251. The TPU-native
mapping:

- set_state('run'/'stop') starts/stops a jax.profiler trace capturing
  device (TPU) and host timelines into a TensorBoard/Perfetto-loadable
  directory (set_config(filename=...)).
- per-op naming: the engine-level op records of the reference come for
  free from XLA's HLO names; ``scope(name)``/Block-level scopes add
  ``jax.named_scope`` annotations so model structure shows up in the
  trace (enable Block scopes with ``profile_symbolic=True``).
- dumps() aggregates the captured chrome-trace events into the
  reference's "aggregate stats" table (per-op total/count/avg device
  time) by parsing the trace the profiler just wrote.
- pause/resume: jax traces cannot pause mid-capture; pause() closes the
  current capture section and resume() opens a new one in the same
  directory (the viewer shows them as separate captures).
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import time
from collections import Counter

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "scope", "host_scope", "state", "scopes_enabled",
           "profiler_set_config", "profiler_set_state"]

_config = {
    "filename": "profile_output",
    "profile_all": False,
    "profile_symbolic": True,   # Block-level named scopes
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": True,
}
_state = "stop"
_scopes_enabled = False


def set_config(**kwargs):
    """Configure the profiler (reference: profiler.py:40 set_config).
    ``filename`` names the output directory (the reference wrote one
    chrome-trace json; jax writes a trace directory loadable by
    TensorBoard, Perfetto, or dumps() below)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise ValueError(f"unknown profiler options: {sorted(unknown)}")
    _config.update(kwargs)


profiler_set_config = set_config


def _trace_dir():
    base = _config["filename"]
    if base.endswith(".json"):
        base = base[:-5]
    return base


def state():
    return _state


def set_state(state_name="stop"):
    """'run' starts a capture, 'stop' ends it (reference: profiler.py:115
    set_state)."""
    global _state, _scopes_enabled
    import jax

    if state_name == "run":
        if _state != "run":
            os.makedirs(_trace_dir(), exist_ok=True)
            jax.profiler.start_trace(_trace_dir())
            _scopes_enabled = bool(_config["profile_symbolic"])
            _state = "run"
    elif state_name == "stop":
        if _state == "run":
            jax.profiler.stop_trace()
            _scopes_enabled = False
            _state = "stop"
    else:
        raise ValueError(f"invalid profiler state {state_name!r}")


profiler_set_state = set_state


def pause(profile_process="worker"):
    """Close the current capture section (reference: profiler.py:146)."""
    set_state("stop")


def resume(profile_process="worker"):
    """Open a new capture section in the same directory (reference:
    profiler.py:160)."""
    set_state("run")


def dump(finished=True, profile_process="worker"):
    """Flush the trace to disk (reference: profiler.py:173). jax writes
    on stop_trace, so this just ensures the capture is stopped."""
    if finished:
        set_state("stop")


def scopes_enabled():
    return _scopes_enabled


class scope:
    """Context manager adding a named scope to the trace (and to HLO op
    metadata under jit). Reference analogue: profiler.Scope /
    MXNET_PROFILER annotations."""

    def __init__(self, name="<unk>:"):
        self._name = name
        self._ctx = None

    def __enter__(self):
        import jax
        self._ctx = jax.named_scope(self._name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


def host_scope(name):
    """Host-timeline span — one API, two sinks. ``scope`` annotates
    *device* ops at trace (jit) time; already-compiled runtime phases —
    serving batch assembly/dispatch, checkpoint IO — happen on the host
    after tracing, so they need a host-side annotation instead. Usable
    on any thread (the serving worker annotates each micro-batch).

    Delegates to :func:`mxnet_tpu.observability.tracing.Tracer.span`,
    which routes to whatever sinks are live: a tracer span when the
    span tracer is enabled (existing host_scope call sites appear in
    ``tracer.export()`` Chrome traces with no second instrumentation),
    a ``jax.profiler.TraceAnnotation`` while a profiler capture runs
    (either way), and a shared no-op singleton when both are off."""
    from .observability.tracing import get_tracer
    return get_tracer().span(name, "host")


def _load_trace_events():
    """Read every chrome-trace json the current trace dir holds."""
    pattern = os.path.join(_trace_dir(), "plugins", "profile", "**",
                           "*.trace.json.gz")
    events = []
    for path in sorted(glob.glob(pattern, recursive=True)):
        try:
            data = json.load(gzip.open(path))
        except Exception:
            continue
        events.extend(data.get("traceEvents", []))
    return events


_DEVICE_HINTS = ("device", "tpu", "gpu", "accelerator")
_HOST_HINTS = ("cpu", "host", "python", "thread")


def _lane_of(pname):
    """Classify a trace process lane as 'device', 'host' or 'unknown'.

    The old heuristic was a bare ``"cpu" in name`` substring test, which
    silently classified every lane matching NEITHER hint set (e.g. a
    plugin runtime's worker lanes) as device time and corrupted the op
    table. Unknown lanes are now an explicit third class: excluded from
    the device table, reported separately."""
    p = pname.lower()
    if any(h in p for h in _DEVICE_HINTS):
        return "device"
    if any(h in p for h in _HOST_HINTS):
        return "host"
    return "unknown"


def dumps(reset=False, format_="table", lane=None):
    """Aggregate stats from the captured trace (reference: profiler.py:194
    dumps): per-op-name total/count/avg time, sorted by total.

    Must be called after set_state('stop'). ``lane`` selects which
    timeline lanes feed the table:

    - ``None`` (default) — device lanes, falling back to host+unknown
      when the capture has no device lane (CPU-only backends);
    - ``'device'`` / ``'host'`` / ``'unknown'`` — exactly that class;
    - ``'both'`` (``format_='dict'`` only) — ``{lane: {"ops": {name:
      (total_us, count)}, "total_us": float, "count": int}}`` for all
      three classes, so host and device totals can be compared without
      re-parsing the trace.

    Returns a printable table, or with ``format_='dict'`` the raw
    ``{name: (total_us, count)}`` mapping.
    """
    events = _load_trace_events()
    pids = {e["pid"]: e["args"].get("name", "")
            for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}

    def aggregate(lanes):
        tot, cnt = Counter(), Counter()
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            if _lane_of(pids.get(e.get("pid"), "")) not in lanes:
                continue
            key = e["name"].split(".")[0]
            tot[key] += e["dur"]
            cnt[key] += 1
        return tot, cnt

    if lane == "both":
        if format_ != "dict":
            raise ValueError("lane='both' requires format_='dict'")
        out = {}
        for cls in ("device", "host", "unknown"):
            tot, cnt = aggregate({cls})
            out[cls] = {"ops": {k: (tot[k], cnt[k]) for k in tot},
                        "total_us": float(sum(tot.values())),
                        "count": int(sum(cnt.values()))}
        return out
    if lane is not None:
        if lane not in ("device", "host", "unknown"):
            raise ValueError(f"invalid lane {lane!r}")
        tot, cnt = aggregate({lane})
    else:
        # prefer accelerator lanes; on a CPU-only backend everything
        # runs on host (or unclassifiable) lanes, so fall back to them
        tot, cnt = aggregate({"device"})
        if not tot:
            tot, cnt = aggregate({"host", "unknown"})
    if format_ == "dict":
        return {k: (tot[k], cnt[k]) for k in tot}
    lines = [f"{'Name':<48} {'Total(us)':>12} {'Count':>8} {'Avg(us)':>10}"]
    lines.append("-" * 80)
    for name, total in tot.most_common():
        lines.append(f"{name[:48]:<48} {total:>12.1f} {cnt[name]:>8} "
                     f"{total / cnt[name]:>10.1f}")
    return "\n".join(lines)
