"""Checkpoint helpers for mx.rnn cells.

Reference: python/mxnet/rnn/rnn.py (save_rnn_checkpoint:32,
load_rnn_checkpoint:62, do_rnn_checkpoint:97). Fused cells store one
flat parameter vector; checkpoints are saved in the UNPACKED per-gate
form so they interchange with unfused stacks (and survive a later
change of fusion strategy), then re-packed on load.
"""
from __future__ import annotations

import warnings

from ..model import save_checkpoint, load_checkpoint
from .rnn_cell import BaseRNNCell

__all__ = ["rnn_unroll", "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def rnn_unroll(cell, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC"):
    """Deprecated alias of ``cell.unroll`` (reference: rnn.py:26 — same
    positional order, so legacy calls keep their meaning). The
    ``input_prefix`` argument only ever named auto-created input
    variables; our unroll names them from the cell prefix, so it is
    accepted and ignored."""
    warnings.warn("rnn_unroll is deprecated; call cell.unroll directly.",
                  DeprecationWarning)
    del input_prefix
    return cell.unroll(length, inputs=inputs, begin_state=begin_state,
                       layout=layout)


def _as_cells(cells):
    return [cells] if isinstance(cells, BaseRNNCell) else list(cells)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """save_checkpoint with fused weights unpacked first
    (reference: rnn.py:32)."""
    for cell in _as_cells(cells):
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """load_checkpoint + re-pack the per-gate arrays into each cell's
    fused form (reference: rnn.py:62)."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _as_cells(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback version (reference: rnn.py:97); drop-in for
    ``mx.callback.do_checkpoint`` in Module.fit."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
