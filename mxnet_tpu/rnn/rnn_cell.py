"""Symbolic RNN cells — the legacy ``mx.rnn`` cell API.

Reference: python/mxnet/rnn/rnn_cell.py (BaseRNNCell:107, RNNCell:361,
LSTMCell:407, GRUCell:468, FusedRNNCell:535, SequentialRNNCell:747,
DropoutCell:826, ResidualCell:956, BidirectionalCell:997). Cells build
Symbol graphs step by step; ``unroll`` lays the recurrence out as an
explicit chain of symbols sharing one parameter set.

TPU-first notes: an unrolled cell graph still lowers to ONE jitted XLA
program through the Symbol executor, so there is no per-step dispatch;
``FusedRNNCell`` instead emits the single ``sym.RNN`` op (lax.scan
inside — better for long sequences, since the unrolled form's program
size grows with T while the fused form's is constant). Gate orders
follow the cuDNN/reference convention (LSTM [i,f,g,o], GRU [r,z,n]) so
packed parameter vectors interchange with the fused op
(ops/rnn.py:14-20).

Conv*Cells and ZoneoutCell are not carried over (niche; gluon.contrib
has the modern equivalents).
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ResidualCell", "BidirectionalCell"]


class RNNParams:
    """Container for cell parameters; shared when passed to several
    cells (reference: rnn_cell.py:77)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.var(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell: one step per ``__call__`` (reference:
    rnn_cell.py:107)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in getattr(self, "_cells", ()):
            cell.reset()

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [e["shape"] for e in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, batch_size=0, **kwargs):
        """Initial states. With ``batch_size`` > 0 they are literal
        zero symbols (or ``func(shape=...)``); with the default 0 they
        are plain variables named ``<prefix>begin_state_<i>`` to be fed
        as data (shape (0, H) placeholders are meaningless under XLA's
        static shapes, so the reference's deferred-batch form maps to
        the feed-as-data idiom its own examples use)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            shape = tuple(batch_size if d == 0 else d
                          for d in info["shape"])
            name = f"{self._prefix}begin_state_{self._init_counter}"
            if func is not None and not batch_size:
                # a (0, H) literal would be a real zero-row array under
                # XLA's static shapes and break far downstream — fail
                # here with the remedy instead
                raise ValueError(
                    "begin_state(func=...) needs batch_size=<N> under "
                    "static shapes; either pass batch_size, or omit "
                    "func (states become named input variables), or "
                    "let unroll(begin_state=None) derive zero states "
                    "from the inputs")
            if func is not None or batch_size:
                make = func or sym.zeros
                states.append(make(shape=shape, name=name, **kwargs))
            else:
                states.append(sym.var(name, shape=None))
        return states

    def _zeros_like_states(self, step_input):
        """States of zeros whose batch dim is inherited from a step
        input symbol — keeps shapes static without knowing B."""
        out = []
        for info in self.state_info:
            width = info["shape"][-1]
            z = sym.mean(step_input * 0.0, axis=-1, keepdims=True)
            out.append(sym.tile(z, reps=(1, width)))
        return out

    def unpack_weights(self, args):
        """Split fused parameter blobs into per-gate arrays (reference:
        rnn_cell.py unpack_weights). Base cells store i2h/h2h blocks
        whole; only gate-splitting is performed."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group in ("i2h", "h2h"):
            for kind in ("weight", "bias"):
                key = f"{self._prefix}{group}_{kind}"
                if key not in args:
                    continue
                blob = args.pop(key)
                for j, gate in enumerate(self._gate_names):
                    args[f"{self._prefix}{group}{gate}_{kind}"] = \
                        blob[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        args = dict(args)
        if not self._gate_names:
            return args
        from ..ndarray import concat as nd_concat
        for group in ("i2h", "h2h"):
            for kind in ("weight", "bias"):
                parts = []
                for gate in self._gate_names:
                    key = f"{self._prefix}{group}{gate}_{kind}"
                    if key in args:
                        parts.append(args.pop(key))
                if parts:
                    args[f"{self._prefix}{group}_{kind}"] = \
                        nd_concat(*parts, dim=0)
        return args

    def unroll(self, length, inputs=None, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll for ``length`` steps (reference: rnn_cell.py:262).

        inputs: one Symbol ((N,T,C) for NTC / (T,N,C) for TNC) or a list
        of per-step (N,C) symbols or None (creates ``t<i>_data`` vars).
        Returns (outputs, states): outputs merged into one symbol along
        the time axis when merge_outputs is True (or None and inputs
        came merged), else a list.
        """
        self.reset()
        axis = layout.find("T")
        came_merged = isinstance(inputs, sym.Symbol)
        if inputs is None:
            inputs = [sym.var(f"{self._prefix}t{i}_data")
                      for i in range(length)]
        elif came_merged:
            inputs = list(sym.split(inputs, num_outputs=length, axis=axis,
                                    squeeze_axis=1))
        assert len(inputs) == length
        if begin_state is None:
            states = self._zeros_like_states(inputs[0])
        else:
            states = list(begin_state)

        outputs = []
        for t in range(length):
            out, states = self(inputs[t], states)
            outputs.append(out)
        if merge_outputs is None:
            merge_outputs = came_merged
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Elman cell: act(W_x x + W_h h + b) (reference: rnn_cell.py:361)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=self._num_hidden,
                                 name=f"{name}h2h")
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gates [i, f, g, o] (reference: rnn_cell.py:407)."""

    def __init__(self, num_hidden, forget_bias=1.0, prefix="lstm_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        from .. import initializer as init_mod
        self._num_hidden = num_hidden
        self._forget_bias = forget_bias
        self._iW = self.params.get("i2h_weight")
        # forget_bias lives in the default i2h_bias initializer
        # (reference: rnn_cell.py:426 init.LSTMBias), NOT in the forward
        # pass — a forward-time add on top of checkpointed biases would
        # double-apply it and break fused/unfused and reference-trained
        # checkpoint agreement
        self._iB = self.params.get(
            "i2h_bias", init=init_mod.LSTMBias(forget_bias=forget_bias))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        h, c = states
        gates = sym.FullyConnected(
            inputs, self._iW, self._iB, num_hidden=4 * self._num_hidden,
            name=f"{name}i2h") + sym.FullyConnected(
            h, self._hW, self._hB, num_hidden=4 * self._num_hidden,
            name=f"{name}h2h")
        i, f, g, o = sym.split(gates, num_outputs=4, axis=-1)
        i = sym.sigmoid(i)
        f = sym.sigmoid(f)
        g = sym.tanh(g)
        o = sym.sigmoid(o)
        next_c = f * c + i * g
        next_h = o * sym.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, gates [r, z, n] (reference: rnn_cell.py:468)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        prev = states[0]
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(prev, self._hW, self._hB,
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}h2h")
        i_r, i_z, i_n = sym.split(i2h, num_outputs=3, axis=-1)
        h_r, h_z, h_n = sym.split(h2h, num_outputs=3, axis=-1)
        r = sym.sigmoid(i_r + h_r)
        z = sym.sigmoid(i_z + h_z)
        n = sym.tanh(i_n + r * h_n)
        next_h = (1.0 - z) * n + z * prev
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """All layers/steps as ONE ``sym.RNN`` op — the lax.scan path
    (reference: rnn_cell.py:535, backed there by cuDNN)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, forget_bias=1.0,
                 get_next_state=False, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._forget_bias = forget_bias
        self._get_next_state = get_next_state
        from .. import initializer as init_mod
        # forget_bias reaches the packed vector through its default
        # initializer (reference: rnn_cell.py:563 init.FusedRNN); the op
        # itself never re-adds it
        self._parameters = self.params.get(
            "parameters",
            init=init_mod.FusedRNN(None, num_hidden=num_hidden,
                                   num_layers=num_layers, mode=mode,
                                   bidirectional=bidirectional,
                                   forget_bias=forget_bias))

    @property
    def state_info(self):
        d = 2 if self._bidirectional else 1
        n = [{"shape": (self._num_layers * d, 0, self._num_hidden),
              "__layout__": "LNC"}]
        if self._mode == "lstm":
            n.append({"shape": (self._num_layers * d, 0, self._num_hidden),
                      "__layout__": "LNC"})
        return n

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    def _zeros_like_states(self, merged_input, axis):
        """(L*D, N, H) zeros with N taken from the input symbol."""
        d = 2 if self._bidirectional else 1
        batch_axis = 1 - axis  # the N axis of the (N,T,C)/(T,N,C) input
        z = sym.mean(merged_input * 0.0, axis=-1, keepdims=False)  # (N,T)/(T,N)
        z = sym.mean(z, axis=1 - batch_axis if batch_axis == 0 else 0,
                     keepdims=True)                                # (N,1)/(1,N)
        if batch_axis == 1:
            z = sym.swapaxes(z, 0, 1)                              # (N,1)
        z = sym.tile(z, reps=(1, self._num_hidden))                # (N,H)
        z = sym.expand_dims(z, axis=0)                             # (1,N,H)
        reps = (self._num_layers * d, 1, 1)
        out = [sym.tile(z, reps=reps)]
        if self._mode == "lstm":
            out.append(sym.tile(z, reps=reps))
        return out

    def _weight_slices(self, input_size):
        """Yield (name, start, stop, shape) over the flat vector in the
        fused op's layout (ops/rnn.py:17-20: all [Wx, Wh] blocks layer-
        major direction-minor, then all [bx, bh] blocks), with the
        per-gate names unfuse()'s cells use."""
        g = len(self._gate_names)
        h = self._num_hidden
        dirs = ("l", "r") if self._bidirectional else ("l",)
        d = len(dirs)
        off = 0
        for layer in range(self._num_layers):
            in_sz = input_size if layer == 0 else h * d
            for dname in dirs:
                cell = f"{self._prefix}{dname}{layer}_"
                for j, gate in enumerate(self._gate_names):
                    yield (f"{cell}i2h{gate}_weight",
                           off + j * h * in_sz, off + (j + 1) * h * in_sz,
                           (h, in_sz))
                off += g * h * in_sz
                for j, gate in enumerate(self._gate_names):
                    yield (f"{cell}h2h{gate}_weight",
                           off + j * h * h, off + (j + 1) * h * h, (h, h))
                off += g * h * h
        for layer in range(self._num_layers):
            for dname in dirs:
                cell = f"{self._prefix}{dname}{layer}_"
                for group in ("i2h", "h2h"):
                    for gate in self._gate_names:
                        yield (f"{cell}{group}{gate}_bias",
                               off, off + h, (h,))
                        off += h

    def _param_size(self, input_size):
        from ..ops.rnn import rnn_param_size
        return rnn_param_size(input_size, self._num_hidden,
                              self._num_layers, self._mode,
                              self._bidirectional)

    def _infer_input_size(self, flat_size):
        """Invert ``_param_size`` for the layer-0 input width given the
        flat packed vector's length."""
        g = len(self._gate_names)
        h = self._num_hidden
        d = 2 if self._bidirectional else 1
        per_rest = (self._num_layers - 1) * d * (g * h * (h * d + h)
                                                 + 2 * g * h)
        layer0 = flat_size - per_rest
        input_size = (layer0 - d * (g * h * h + 2 * g * h)) // (d * g * h)
        assert self._param_size(input_size) == flat_size, \
            f"parameter vector size {flat_size} does not match any " \
            f"input width for this cell"
        return input_size

    def unpack_weights(self, args):
        """Split the flat '<prefix>parameters' vector into the per-gate
        arrays unfuse()'s cells bind (reference: rnn_cell.py:638)."""
        from .. import ndarray as nd
        args = dict(args)
        key = f"{self._prefix}parameters"
        if key not in args:
            return args
        flat = args.pop(key)
        flat = flat.asnumpy() if hasattr(flat, "asnumpy") else flat
        input_size = self._infer_input_size(flat.size)
        for name, start, stop, shape in self._weight_slices(input_size):
            args[name] = nd.array(flat[start:stop].reshape(shape))
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights (reference: rnn_cell.py:650)."""
        import numpy as _np
        from .. import ndarray as nd
        args = dict(args)
        w0 = args[f"{self._prefix}l0_i2h{self._gate_names[0]}_weight"]
        input_size = w0.shape[1]
        flat = _np.zeros(self._param_size(input_size), _np.float32)
        for name, start, stop, shape in self._weight_slices(input_size):
            part = args.pop(name)
            part = part.asnumpy() if hasattr(part, "asnumpy") else part
            flat[start:stop] = part.reshape(-1)
        args[f"{self._prefix}parameters"] = nd.array(flat)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped; use unroll() "
            "(reference has the same restriction)")

    def unroll(self, length, inputs=None, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            inputs = sym.stack(*inputs, axis=axis)
        elif inputs is None:
            inputs = sym.var(f"{self._prefix}data")
        if begin_state is None:
            states = self._zeros_like_states(inputs, axis)
        else:
            states = list(begin_state)
        tnc = sym.swapaxes(inputs, 0, 1) if axis == 1 else inputs
        rnn = sym.RNN(tnc, self._parameters, states[0],
                      *(states[1:] if self._mode == "lstm" else ()),
                      state_size=self._num_hidden,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=self._get_next_state,
                      name=f"{self._prefix}rnn")
        out = rnn[0]
        # reference contract (rnn_cell.py:700-707): states is [] unless
        # get_next_state was requested, in which case it is the FINAL
        # hidden (and cell) state — never the begin states
        if not self._get_next_state:
            next_states = []
        elif self._mode == "lstm":
            next_states = [rnn[1], rnn[2]]
        else:
            next_states = [rnn[1]]
        if axis == 1:
            out = sym.swapaxes(out, 0, 1)
        if merge_outputs is False:
            out = list(sym.split(out, num_outputs=length, axis=axis,
                                 squeeze_axis=1))
        return out, next_states

    def unfuse(self):
        """Equivalent stack of unfused cells (reference:
        rnn_cell.py:735): same gate math, stepping-capable."""
        stack = SequentialRNNCell()
        make = {"rnn_relu": lambda p: RNNCell(self._num_hidden, "relu", p),
                "rnn_tanh": lambda p: RNNCell(self._num_hidden, "tanh", p),
                # forget_bias=0: the packed vector already holds the
                # trained biases, so a fresh init of the unfused cells
                # must not re-apply the forget-gate offset
                "lstm": lambda p: LSTMCell(self._num_hidden,
                                           forget_bias=0.0, prefix=p),
                "gru": lambda p: GRUCell(self._num_hidden, prefix=p)
                }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make(f"{self._prefix}l{i}_"),
                    make(f"{self._prefix}r{i}_")))
            else:
                stack.add(make(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack cells vertically (reference: rnn_cell.py:747)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        return self

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        assert not self._modified
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unpack_weights(self, args):
        for c in self._cells:
            args = c.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for c in self._cells:
            args = c.pack_weights(args)
        return args

    def _split_states(self, states):
        out, i = [], 0
        for c in self._cells:
            n = len(c.state_info)
            out.append(states[i:i + n])
            i += n
        return out

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        for cell, s in zip(self._cells, self._split_states(states)):
            inputs, ns = cell(inputs, s)
            next_states.extend(ns)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout on the step output (reference: rnn_cell.py:826)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = sym.Dropout(inputs, p=self._dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Wrap a cell, reusing its parameters (reference:
    rnn_cell.py:866)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ResidualCell(ModifierCell):
    """output = cell(x) + x (reference: rnn_cell.py:956)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(BaseRNNCell):
    """Run two cells over opposite directions; concat outputs
    (reference: rnn_cell.py:997). Step-calling is impossible (the
    backward direction needs the whole sequence) — unroll only."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._cells = [l_cell, r_cell]
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return [i for c in self._cells for i in c.state_info]

    def begin_state(self, **kwargs):
        assert not self._modified
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unpack_weights(self, args):
        for c in self._cells:
            args = c.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for c in self._cells:
            args = c.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll()")

    def unroll(self, length, inputs=None, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        came_merged = isinstance(inputs, sym.Symbol)
        if inputs is None:
            inputs = [sym.var(f"bi_t{i}_data") for i in range(length)]
        elif came_merged:
            inputs = list(sym.split(inputs, num_outputs=length, axis=axis,
                                    squeeze_axis=1))
        l_cell, r_cell = self._cells
        nl = len(l_cell.state_info)
        begin_l = begin_state[:nl] if begin_state is not None else None
        begin_r = begin_state[nl:] if begin_state is not None else None
        l_out, l_states = l_cell.unroll(
            length, inputs, begin_l, layout, merge_outputs=False)
        r_out, r_states = r_cell.unroll(
            length, list(reversed(inputs)), begin_r, layout,
            merge_outputs=False)
        outputs = [sym.concat(lo, ro, dim=-1,
                              name=f"{self._output_prefix}t{t}")
                   for t, (lo, ro) in enumerate(
                       zip(l_out, reversed(r_out)))]
        if merge_outputs is None:
            merge_outputs = came_merged
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
