"""mx.rnn — legacy symbolic RNN cell API + bucketing iterator.

Reference: python/mxnet/rnn/ (rnn_cell.py, io.py). The modern path is
``gluon.rnn``; this package exists so reference Module-era RNN code
(stacked cells, FusedRNNCell, BucketSentenceIter) ports unchanged.
"""
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, ModifierCell,
                       ResidualCell, RNNCell, RNNParams,
                       SequentialRNNCell)
from .io import BucketSentenceIter, encode_sentences
from .rnn import (do_rnn_checkpoint, load_rnn_checkpoint, rnn_unroll,
                  save_rnn_checkpoint)

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ResidualCell", "BidirectionalCell",
           "BucketSentenceIter", "encode_sentences", "rnn_unroll",
           "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]
