"""Bucketing data iterator for the legacy RNN API.

Reference: python/mxnet/rnn/io.py (encode_sentences:29,
BucketSentenceIter:83). Sentences are binned into the smallest bucket
that fits, padded with ``invalid_label``, and served as
(data, shifted-label) batches carrying a ``bucket_key`` — each bucket
key is one static-shape XLA program on the consuming BucketingModule.
"""
from __future__ import annotations

import bisect
import random

import numpy as np

from .. import ndarray
from ..io.io import DataBatch, DataDesc, DataIter

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Map token sentences to int ids, growing ``vocab`` as needed
    (reference: io.py:29). Returns (encoded, vocab)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
        idx = max(vocab.values()) + 1
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    if unknown_token is None:
                        raise ValueError(
                            f"unknown token {word!r} with a fixed vocab "
                            "and no unknown_token")
                    if unknown_token not in vocab:
                        # mutating a fixed vocab would push ids past the
                        # embedding width trained against it
                        raise ValueError(
                            f"unknown_token {unknown_token!r} must "
                            "already be in the fixed vocab")
                    word = unknown_token
                else:
                    if idx == invalid_label:
                        idx += 1
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed LM iterator: label[t] = data[t+1] (reference:
    io.py:83)."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", layout="NT"):
        super().__init__(batch_size=batch_size)
        if not buckets:
            counts = np.bincount([len(s) for s in sentences])
            buckets = [length for length, n in enumerate(counts)
                       if n >= batch_size]
        buckets = sorted(buckets)

        binned = [[] for _ in buckets]
        discarded = 0
        for sent in sentences:
            i = bisect.bisect_left(buckets, len(sent))
            if i == len(buckets):
                discarded += 1
                continue
            row = np.full((buckets[i],), invalid_label, dtype=dtype)
            row[:len(sent)] = sent
            binned[i].append(row)
        if discarded:
            print(f"WARNING: discarded {discarded} sentences longer than "
                  "the largest bucket.")
        keep = [i for i, rows in enumerate(binned) if rows]
        if not keep:
            if buckets and discarded:
                raise ValueError(
                    f"no bucket holds any sentence: all {discarded} "
                    f"sentences are longer than the largest bucket "
                    f"({buckets[-1]}) — add a larger bucket")
            raise ValueError(
                "no bucket holds any sentence: auto-bucketing keeps "
                "only lengths occurring >= batch_size times — pass "
                "explicit `buckets` or lower batch_size")
        self.buckets = [buckets[i] for i in keep]
        self.data = [np.asarray(binned[i], dtype=dtype) for i in keep]

        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find("N")
        if self.major_axis not in (0, 1):
            raise ValueError(f"invalid layout {layout!r}: need NT or TN")
        self.default_bucket_key = max(self.buckets)
        self.provide_data = [DataDesc(
            data_name, self._shape(self.default_bucket_key), layout=layout)]
        self.provide_label = [DataDesc(
            label_name, self._shape(self.default_bucket_key), layout=layout)]

        self.idx = [(i, j) for i, rows in enumerate(self.data)
                    for j in range(0, len(rows) - batch_size + 1,
                                   batch_size)]
        self.curr_idx = 0
        self.reset()

    def _shape(self, seq_len):
        return ((self.batch_size, seq_len) if self.major_axis == 0
                else (seq_len, self.batch_size))

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for rows in self.data:
            np.random.shuffle(rows)
        self.nddata, self.ndlabel = [], []
        for rows in self.data:
            label = np.full_like(rows, self.invalid_label)
            label[:, :-1] = rows[:, 1:]
            self.nddata.append(ndarray.array(rows, dtype=self.dtype))
            self.ndlabel.append(ndarray.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        if self.major_axis == 1:
            data = data.T
            label = label.T
        key = self.buckets[i]
        return DataBatch(
            [data], [label], pad=0, bucket_key=key,
            provide_data=[DataDesc(self.data_name, self._shape(key),
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, self._shape(key),
                                    layout=self.layout)])
