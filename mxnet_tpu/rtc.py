"""mx.rtc — runtime kernel compilation (the Pallas escape hatch).

Reference: python/mxnet/rtc.py (CudaModule/CudaKernel:28 — compile CUDA
C source at runtime and launch it on arrays). The TPU-native analogue
compiles Pallas kernels: a user writes a Python kernel body against
``pl.BlockSpec`` refs, registers it, and calls it like any other
operator (nd.*, inside hybridized blocks, under jit). On non-TPU
backends the kernel runs in Pallas interpret mode, so the same code
tests on CPU and compiles to Mosaic on TPU — the role runtime CUDA
compilation played in the reference.

    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def scale_add(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + y_ref[...]

    mx.rtc.register_pallas_op("my_scale_add", scale_add)
    out = mx.nd.my_scale_add(a, b)

``CudaModule`` is kept as a named stub that points here, so reference
code fails with a actionable message rather than an AttributeError.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["register_pallas_op", "CudaModule"]


def _default_out(shapes, dtypes):
    return shapes[0], dtypes[0]


def register_pallas_op(name, kernel, out_shape=None, grid=None,
                       in_specs=None, out_specs=None, reference_fn=None,
                       interpret=None):
    """Register a Pallas kernel as a framework operator.

    - ``kernel(*in_refs, out_ref)``: Pallas kernel body.
    - ``out_shape``: callable (shapes, dtypes) -> (shape, dtype); default
      mirrors input 0 (elementwise kernels).
    - ``grid``/``in_specs``/``out_specs``: forwarded to pallas_call for
      blocked kernels; omitted = whole-array refs.
    - ``reference_fn``: optional plain-jnp implementation of the same
      math. When given, the op is differentiable: the Pallas kernel runs
      the forward and the backward is jax.vjp of ``reference_fn`` — the
      same custom_vjp pattern ops/flash_attention.py uses (Pallas has no
      generic reverse-mode rule). Without it, the op is forward-only.
    - ``interpret``: force interpret mode; default auto (interpret
      everywhere except real TPU backends).

    Returns the op name; the op is immediately available as ``nd.<name>``
    and in Symbol/Gluon.
    """
    import jax
    from jax.experimental import pallas as pl

    shape_fn = out_shape or _default_out

    def run_kernel(*arrays):
        shapes = [tuple(a.shape) for a in arrays]
        dtypes = [a.dtype for a in arrays]
        oshape, odtype = shape_fn(shapes, dtypes)
        if interpret is None:
            interp = jax.default_backend() not in ("tpu",)
        else:
            interp = interpret
        call_kwargs = {}
        if grid is not None:
            call_kwargs["grid"] = grid
        if in_specs is not None:
            call_kwargs["in_specs"] = in_specs
        if out_specs is not None:
            call_kwargs["out_specs"] = out_specs
        fn = pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct(oshape, odtype),
            interpret=interp, **call_kwargs)
        return fn(*arrays)

    if reference_fn is not None:
        @jax.custom_vjp
        def core(*arrays):
            return run_kernel(*arrays)

        def core_fwd(*arrays):
            return run_kernel(*arrays), arrays

        def core_bwd(res, g):
            _, vjp = jax.vjp(reference_fn, *res)
            return vjp(g)

        core.defvjp(core_fwd, core_bwd)
        impl = lambda *arrays, **kw: core(*arrays)   # noqa: E731
        differentiable = True
    else:
        impl = lambda *arrays, **kw: run_kernel(*arrays)  # noqa: E731
        differentiable = False

    from .ops.registry import _REGISTRY, Operator
    _REGISTRY[name] = Operator(name, impl,
                               differentiable=differentiable)
    from . import ndarray as _nd
    from .ndarray.register import make_op_func
    setattr(_nd, name, make_op_func(_REGISTRY[name]))
    return name


class CudaModule:
    """Reference rtc.CudaModule compiled CUDA C at runtime; there is no
    CUDA on this backend. Use register_pallas_op (same capability,
    TPU-native)."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "CUDA RTC does not exist on the TPU build; write the kernel "
            "in Pallas and mx.rtc.register_pallas_op it (module "
            "docstring has a template)")
