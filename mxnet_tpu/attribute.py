"""mx.attribute — AttrScope for symbol attribute injection.

Reference: python/mxnet/attribute.py (AttrScope:26 — a thread-local
stack of attribute dicts applied to every Symbol created inside the
scope; used for ctx_group model-parallel hints, __lr_mult__, etc.).
Symbols here store the merged attributes in ``_attr``; sharded
placement is expressed with jax.sharding instead of ctx_group, but the
attributes round-trip through save/load for tooling parity.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current", "get_current_attrs"]

_TLS = threading.local()


def _stack():
    if not hasattr(_TLS, "stack"):
        _TLS.stack = []
    return _TLS.stack


class AttrScope:
    """``with AttrScope(__lr_mult__='2.0'):`` attaches attributes to
    every Symbol created in the scope (reference: attribute.py:26)."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("attributes must be strings "
                                 "(reference AttrScope check)")
        self._attr = kwargs

    def get(self, attr=None):
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def current():
    stack = _stack()
    return stack[-1] if stack else None


def get_current_attrs(extra=None):
    """Merged attributes of every active scope, innermost last."""
    out = {}
    for scope in _stack():
        out.update(scope._attr)
    if extra:
        out.update(extra)
    return out
