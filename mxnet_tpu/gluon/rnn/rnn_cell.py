"""Recurrent cells: single-step building blocks + unrolling.

Reference: python/mxnet/gluon/rnn/rnn_cell.py. Cells hold per-gate
parameters with the reference's naming (i2h_weight/h2h_weight/...) and
gate order (LSTM [i,f,g,o], GRU [r,z,n]) so layer/cell checkpoints
interchange with the fused op (ops/rnn.py). ``unroll`` is a trace-time
Python loop — under hybridize it compiles to one XLA program; the fused
layers (rnn_layer.py) use ``lax.scan`` instead and are the fast path.
"""
from __future__ import annotations

from ..block import HybridBlock
from ...ndarray import NDArray

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of (N, C) steps; returns
    (steps, axis, batch_size)."""
    assert layout in ("TNC", "NTC")
    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        steps = list(inputs)
    else:
        if axis == 1:
            inputs = inputs.swapaxes(0, 1)
        length = length or inputs.shape[0]
        steps = [inputs[t] for t in range(length)]
    return steps, axis, steps[0].shape[0]


def _merge_outputs(outputs, axis):
    from ... import ndarray as F
    stacked = F.stack(list(outputs), axis=0)
    return stacked.swapaxes(0, 1) if axis == 1 else stacked


class RecurrentCell(HybridBlock):
    """Base recurrent cell (reference: rnn_cell.py:81).

    A cell maps ``(input_t, states) -> (output_t, new_states)``.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Zero (or ``func``-built) initial states."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        from ... import ndarray as F
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            if func is None:
                states.append(F.zeros(shape, **kwargs))
            else:
                states.append(func(shape=shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell ``length`` steps (reference: rnn_cell.py:186)."""
        self.reset()
        steps, axis, batch = _format_sequence(length, inputs, layout, None)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch)
        states = begin_state
        outputs = []
        step_states = []  # per-step states, for SequenceLast on valid_length
        for t in range(length):
            out, states = self(steps[t], states)
            outputs.append(out)
            if valid_length is not None:
                step_states.append(states)
        if valid_length is not None:
            from ... import ndarray as F
            stacked = F.stack(outputs, axis=0)
            masked = F.SequenceMask(stacked, valid_length,
                                    use_sequence_length=True)
            outputs = [masked[t] for t in range(length)]
            # final states come from each sample's LAST VALID step, not the
            # last padded step (reference: rnn_cell.py unroll SequenceLast)
            states = [
                F.SequenceLast(F.stack([s[i] for s in step_states], axis=0),
                               valid_length, use_sequence_length=True)
                for i in range(len(states))]
        if merge_outputs is None or merge_outputs:
            return _merge_outputs(outputs, axis), states
        return outputs, states

    def forward(self, x, *args):
        self._counter += 1
        return super().forward(x, *args)


class HybridRecurrentCell(RecurrentCell):
    """Alias tier kept for API parity (all cells here are hybrid)."""


class _GatedCell(HybridRecurrentCell):
    """Shared parameter layout for RNN/LSTM/GRU cells."""

    def __init__(self, hidden_size, gates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._gates = gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(gates * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(gates * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(gates * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(gates * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (self._gates * self._hidden_size,
                                 x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]


class RNNCell(_GatedCell):
    """Elman cell: ``h' = act(W_x x + b_x + W_h h + b_h)``
    (reference: rnn_cell.py:344)."""

    def __init__(self, hidden_size, activation="tanh", **kwargs):
        super().__init__(hidden_size, gates=1, **kwargs)
        self._activation = activation

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, x, states, i2h_weight=None, h2h_weight=None,
                       i2h_bias=None, h2h_bias=None):
        h = self._hidden_size
        pre = (F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=h)
               + F.FullyConnected(states[0], h2h_weight, h2h_bias,
                                  num_hidden=h))
        out = F.Activation(pre, act_type=self._activation)
        return out, [out]


class LSTMCell(_GatedCell):
    """LSTM cell, gate order [i, f, g, o] (reference: rnn_cell.py:439)."""

    def __init__(self, hidden_size, **kwargs):
        super().__init__(hidden_size, gates=4, **kwargs)

    def _alias(self):
        return "lstm"

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, x, states, i2h_weight=None, h2h_weight=None,
                       i2h_bias=None, h2h_bias=None):
        h = self._hidden_size
        gates = (F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * h)
                 + F.FullyConnected(states[0], h2h_weight, h2h_bias,
                                    num_hidden=4 * h))
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c = f * states[1] + i * g
        out = o * F.tanh(c)
        return out, [out, c]


class GRUCell(_GatedCell):
    """GRU cell, gate order [r, z, n] (reference: rnn_cell.py:565)."""

    def __init__(self, hidden_size, **kwargs):
        super().__init__(hidden_size, gates=3, **kwargs)

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, x, states, i2h_weight=None, h2h_weight=None,
                       i2h_bias=None, h2h_bias=None):
        h = self._hidden_size
        xp = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * h)
        hp = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                              num_hidden=3 * h)
        xr, xz, xn = F.split(xp, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(hp, num_outputs=3, axis=-1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.tanh(xn + r * hn)
        out = (1 - z) * n + z * states[0]
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells applied in sequence each step
    (reference: rnn_cell.py:646)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, x, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            x, s = cell(x, states[p:p + n])
            p += n
            next_states.extend(s)
        return x, next_states

    def hybrid_forward(self, F, x, states):  # pragma: no cover - forward()
        raise RuntimeError("SequentialRNNCell dispatches in forward()")


HybridSequentialRNNCell = SequentialRNNCell


class DropoutCell(RecurrentCell):
    """Applies dropout to the input each step (reference: rnn_cell.py:741)."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, x, states):
        if self._rate > 0:
            x = F.Dropout(x, p=self._rate)
        return x, states


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py:790)."""

    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + "mod_")
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: rnn_cell.py:849)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, x, states):
        from ... import ndarray as F
        from ... import autograd
        out, next_states = self.base_cell(x, states)
        if autograd.is_training():
            if self._zo > 0:
                prev = self._prev_output
                if prev is None:
                    prev = F.zeros_like(out)
                # Dropout of ones -> 0 where zoned out, keep prev there
                keep = F.Dropout(F.ones_like(out), p=self._zo)
                out = F.where(keep, out, prev)
            if self._zs > 0:
                next_states = [
                    F.where(F.Dropout(F.ones_like(ns), p=self._zs), ns, s)
                    for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states

    def hybrid_forward(self, F, x, states):  # pragma: no cover
        raise RuntimeError("ZoneoutCell dispatches in forward()")


class ResidualCell(ModifierCell):
    """Adds the input to the cell output (reference: rnn_cell.py:914)."""

    def hybrid_forward(self, F, x, states):
        out, states = self.base_cell(x, states)
        return out + x, states


class BidirectionalCell(RecurrentCell):
    """Runs two cells over the sequence in opposite directions; only
    usable through ``unroll`` (reference: rnn_cell.py:957)."""

    def __init__(self, l_cell, r_cell):
        super().__init__(prefix="bi_")
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        steps, axis, batch = _format_sequence(length, inputs, layout, None)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch)
        l_cell, r_cell = self._children.values()
        nl = len(l_cell.state_info())
        from ... import ndarray as F
        if valid_length is None:
            rev_steps = list(reversed(steps))
        else:
            # per-sample reversal that keeps padding at the tail, so the
            # reverse cell sees real tokens first (plain reversed() would
            # feed it padding)
            rev = F.SequenceReverse(F.stack(steps, axis=0), valid_length,
                                    use_sequence_length=True)
            rev_steps = [rev[t] for t in range(length)]
        l_out, l_states = l_cell.unroll(
            length, steps, begin_state[:nl], layout="TNC",
            merge_outputs=False, valid_length=valid_length)
        r_out, r_states = r_cell.unroll(
            length, rev_steps, begin_state[nl:], layout="TNC",
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_out = list(reversed(r_out))
        else:
            rback = F.SequenceReverse(F.stack(r_out, axis=0), valid_length,
                                      use_sequence_length=True)
            r_out = [rback[t] for t in range(length)]
        outputs = [F.concat([lo, ro], dim=-1)
                   for lo, ro in zip(l_out, r_out)]
        out = _merge_outputs(outputs, axis) if merge_outputs in (None, True) \
            else outputs
        return out, l_states + r_states
