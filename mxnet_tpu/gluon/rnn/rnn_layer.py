"""Fused recurrent layers: RNN / LSTM / GRU.

Reference: python/mxnet/gluon/rnn/rnn_layer.py (thin wrappers over the
fused RNN op, src/operator/rnn-inl.h). Parameters are held per
layer/direction with the reference's names ({l,r}{i}_i2h_weight, ...) and
packed into the fused op's flat vector at trace time — the packing is pure
reshape/concat, free under XLA, so checkpoints stay interchangeable while
the compute path is the lax.scan program in ops/rnn.py.
"""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be TNC or NTC"
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _GATES[mode]
        with self.name_scope():
            for layer in range(num_layers):
                in_sz = input_size if layer == 0 else \
                    hidden_size * self._dir
                for d, tag in enumerate(["l", "r"][:self._dir]):
                    g = self._gates * hidden_size
                    setattr(self, f"{tag}{layer}_i2h_weight",
                            self.params.get(
                                f"{tag}{layer}_i2h_weight",
                                shape=(g, in_sz),
                                init=i2h_weight_initializer,
                                allow_deferred_init=True))
                    setattr(self, f"{tag}{layer}_h2h_weight",
                            self.params.get(
                                f"{tag}{layer}_h2h_weight",
                                shape=(g, hidden_size),
                                init=h2h_weight_initializer,
                                allow_deferred_init=True))
                    setattr(self, f"{tag}{layer}_i2h_bias",
                            self.params.get(
                                f"{tag}{layer}_i2h_bias", shape=(g,),
                                init=i2h_bias_initializer,
                                allow_deferred_init=True))
                    setattr(self, f"{tag}{layer}_h2h_bias",
                            self.params.get(
                                f"{tag}{layer}_h2h_bias", shape=(g,),
                                init=h2h_bias_initializer,
                                allow_deferred_init=True))

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_size or None} -> "
                f"{self._hidden_size}, {self._layout}, "
                f"num_layers={self._num_layers}"
                f"{', bidirectional' if self._dir == 2 else ''})")

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size,
                 self._hidden_size)
        if self._mode == "lstm":
            return [{"shape": shape, "__layout__": "LNC"},
                    {"shape": shape, "__layout__": "LNC"}]
        return [{"shape": shape, "__layout__": "LNC"}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        states = []
        for info in self.state_info(batch_size):
            if func is None:
                states.append(F.zeros(info["shape"], **kwargs))
            else:
                states.append(func(shape=info["shape"], **kwargs))
        return states

    def _infer_param_shapes(self, x, *args):
        in_sz = x.shape[2] if self._layout == "TNC" else x.shape[-1]
        g = self._gates * self._hidden_size
        for tag in ["l", "r"][:self._dir]:
            getattr(self, f"{tag}0_i2h_weight").shape = (g, in_sz)

    def _flat_params(self, F, kwargs):
        """Pack per-layer params into the fused op's flat vector
        (weights first, then biases; layer-major, direction-minor —
        reference: rnn-inl.h GetRnnParamSize ordering)."""
        chunks = []
        for layer in range(self._num_layers):
            for tag in ["l", "r"][:self._dir]:
                chunks.append(kwargs[f"{tag}{layer}_i2h_weight"]
                              .reshape((-1,)))
                chunks.append(kwargs[f"{tag}{layer}_h2h_weight"]
                              .reshape((-1,)))
        for layer in range(self._num_layers):
            for tag in ["l", "r"][:self._dir]:
                chunks.append(kwargs[f"{tag}{layer}_i2h_bias"])
                chunks.append(kwargs[f"{tag}{layer}_h2h_bias"])
        return F.concat(chunks, dim=0)

    def hybrid_forward(self, F, x, *args, **kwargs):
        states = args[0] if args else None
        skip_states = states is None
        if skip_states:
            batch = x.shape[0] if self._layout == "NTC" else x.shape[1]
            states = self.begin_state(batch, dtype=x.dtype)
        if not isinstance(states, (list, tuple)):
            states = [states]
        if self._layout == "NTC":
            x = x.swapaxes(0, 1)
        params = self._flat_params(F, kwargs)
        h0 = states[0]
        c0 = states[1] if self._mode == "lstm" else None
        rnn_args = [x, params, h0] + ([c0] if c0 is not None else [])
        out, hT, cT = F.RNN(*rnn_args, state_size=self._hidden_size,
                            num_layers=self._num_layers, mode=self._mode,
                            bidirectional=self._dir == 2, p=self._dropout,
                            state_outputs=True)
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        out_states = [hT, cT] if self._mode == "lstm" else [hT]
        return out if skip_states else (out, out_states)


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (reference: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        super().__init__(f"rnn_{activation}", hidden_size, num_layers,
                         layout, dropout, bidirectional,
                         input_size=input_size, **kwargs)


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size=input_size, **kwargs)


class GRU(_RNNLayer):
    """Multi-layer GRU (reference: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size=input_size, **kwargs)
