"""Gluon utilities.

Reference: python/mxnet/gluon/utils.py — split_data/split_and_load (the
data-parallel batch slicer feeding per-GPU executors), clip_global_norm,
check_sha1, download. ``split_and_load`` is kept for reference-code parity;
the TPU-idiomatic path is a single sharded array over a Mesh
(mxnet_tpu.parallel.shard_batch).
"""
from __future__ import annotations

import hashlib
import os

import numpy as _np

from ..context import Context
from ..ndarray import NDArray, array as nd_array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice slices
    (reference: gluon/utils.py:37)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch "
            f"size that's multiple of {num_slice} or set even_split=False "
            "to allow uneven partitioning of data.")
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and load slices onto contexts (reference: gluon/utils.py:95)."""
    if not isinstance(data, NDArray):
        data = nd_array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the 2-norm of the concatenation is at most
    max_norm (reference: gluon/utils.py:132)."""
    assert len(arrays) > 0
    ctx = arrays[0].context
    total = None
    for arr in arrays:
        n = (arr.as_in_context(ctx) * arr.as_in_context(ctx)).sum()
        total = n if total is None else total + n
    total_norm = total.sqrt()
    if check_isfinite:
        tn = float(total_norm.asscalar())
        if not _np.isfinite(tn):
            import warnings
            warnings.warn("nan or inf is detected. Clipping results will "
                          "be undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    one = nd_array(_np.ones(1, dtype="float32"), ctx=ctx)
    scale = (scale < 1.0) * scale + (scale >= 1.0) * one
    for arr in arrays:
        arr *= scale.as_in_context(arr.context)
    if check_isfinite:
        return tn
    return total_norm


def check_sha1(filename, sha1_hash):
    """Check file sha1 (reference: gluon/utils.py:185)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Download a file (reference: gluon/utils.py:205). This build runs in
    a zero-egress environment; the function exists for API parity and
    raises unless the file is already present locally."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    raise RuntimeError(
        f"download of {url} unavailable: no network egress in this "
        f"environment. Place the file at {fname} manually.")
