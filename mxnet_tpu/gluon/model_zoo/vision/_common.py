"""Shared pretrained-weight loading for the vision zoo."""


def load_pretrained(net, pretrained, params_file, ctx=None):
    """Load local pretrained weights or fail with an actionable error
    (this environment has no network egress — reference get_model_file
    downloaded from the model store)."""
    if not pretrained:
        return net
    if not params_file:
        raise RuntimeError(
            "pretrained weights require a local params_file= path "
            "(no network egress in this environment)")
    net.load_parameters(params_file, ctx=ctx)
    return net
