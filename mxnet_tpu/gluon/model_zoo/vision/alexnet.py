"""AlexNet (reference: python/mxnet/gluon/model_zoo/vision/alexnet.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (Conv2D, Dense, Dropout, Flatten, HybridSequential,
                   MaxPool2D)

__all__ = ["AlexNet", "alexnet"]


class AlexNet(HybridBlock):
    """AlexNet (reference: alexnet.py:33)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            with self.features.name_scope():
                self.features.add(Conv2D(64, kernel_size=11, strides=4,
                                         padding=2, activation="relu"))
                self.features.add(MaxPool2D(pool_size=3, strides=2))
                self.features.add(Conv2D(192, kernel_size=5, padding=2,
                                         activation="relu"))
                self.features.add(MaxPool2D(pool_size=3, strides=2))
                self.features.add(Conv2D(384, kernel_size=3, padding=1,
                                         activation="relu"))
                self.features.add(Conv2D(256, kernel_size=3, padding=1,
                                         activation="relu"))
                self.features.add(Conv2D(256, kernel_size=3, padding=1,
                                         activation="relu"))
                self.features.add(MaxPool2D(pool_size=3, strides=2))
                self.features.add(Flatten())
                self.features.add(Dense(4096, activation="relu"))
                self.features.add(Dropout(0.5))
                self.features.add(Dense(4096, activation="relu"))
                self.features.add(Dropout(0.5))
            self.output = Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def alexnet(pretrained=False, ctx=None, **kwargs):
    from ._common import load_pretrained
    pf = kwargs.pop("params_file", None)
    return load_pretrained(AlexNet(**kwargs), pretrained, pf, ctx)
