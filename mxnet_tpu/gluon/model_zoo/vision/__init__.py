"""Vision model zoo (reference: python/mxnet/gluon/model_zoo/vision/).

``get_model(name)`` resolves by registry like the reference's
model_zoo/vision/__init__.py get_model.
"""
# module refs captured before star-imports (which shadow e.g. `alexnet`
# with the constructor function of the same name)
from . import resnet as _resnet
from . import alexnet as _alexnet
from . import vgg as _vgg
from . import squeezenet as _squeezenet
from . import densenet as _densenet
from . import mobilenet as _mobilenet
from . import inception as _inception

from .resnet import *  # noqa: F401,F403,E402
from .alexnet import *  # noqa: F401,F403,E402
from .vgg import *  # noqa: F401,F403,E402
from .squeezenet import *  # noqa: F401,F403,E402
from .densenet import *  # noqa: F401,F403,E402
from .mobilenet import *  # noqa: F401,F403,E402
from .inception import *  # noqa: F401,F403,E402

_models = {}
for _m in (_resnet, _alexnet, _vgg, _squeezenet, _densenet, _mobilenet,
           _inception):
    for _name in _m.__all__:
        _obj = getattr(_m, _name)
        if callable(_obj) and _name[0].islower():
            _models[_name] = _obj


def get_model(name, **kwargs):
    """Resolve a model constructor by name (reference:
    model_zoo/vision/__init__.py:89)."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"Model {name} is not supported. Available: "
            f"{sorted(_models.keys())}")
    return _models[name](**kwargs)
