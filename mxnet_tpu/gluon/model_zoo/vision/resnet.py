"""ResNet V1/V2 model families.

Reference: python/mxnet/gluon/model_zoo/vision/resnet.py (BasicBlockV1/V2,
BottleneckV1/V2, ResNetV1/V2, resnet18..152_v1/v2). Same layer specs and
param names as the reference; parameters are stored/loaded in this repo's
own MXTPU1 container format (see ndarray save/load), not the reference's
binary NDArray format. TPU-first knobs: ``layout='NHWC'`` builds the whole
net channels-last (weights OHWI, BatchNorm axis=-1) — ~2x faster training
on v5e than NCHW — and bf16 via net.cast('bfloat16').
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (BatchNorm, Conv2D, Dense, GlobalAvgPool2D, HybridSequential,
                   MaxPool2D, Activation)

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                  use_bias=False, in_channels=in_channels, layout=layout)


def _bn(layout="NCHW", **kw):
    return BatchNorm(axis=layout.index("C"), **kw)


class _S2DStemConv(HybridBlock):
    """MLPerf-style stem: the 7x7/s2/p3 conv evaluated as an equivalent
    4x4/s1 conv over a 2x2 space-to-depth input (ops/nn.py
    _s2d_stem_conv). Holds the standard OHWI (O,7,7,3) weight, so
    checkpoints are interchangeable with the plain-conv stem. NHWC only."""

    def __init__(self, channels, in_channels=3, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, 7, 7, in_channels),
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight=None):
        return F._s2d_stem_conv(x, weight)


class BasicBlockV1(HybridBlock):
    """Pre-2016 residual block (reference: resnet.py:40)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self.body = HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        self.body.add(_bn(layout))
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels, layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1,
                                       strides=stride, use_bias=False,
                                       in_channels=in_channels,
                                       layout=layout))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    """Bottleneck block (reference: resnet.py:85)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self.body = HybridSequential(prefix="")
        self.body.add(Conv2D(channels // 4, kernel_size=1, strides=stride,
                             layout=layout))
        self.body.add(_bn(layout))
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
        self.body.add(_bn(layout))
        self.body.add(Activation("relu"))
        self.body.add(Conv2D(channels, kernel_size=1, strides=1,
                             layout=layout))
        self.body.add(_bn(layout))
        if downsample:
            self.downsample = HybridSequential(prefix="")
            self.downsample.add(Conv2D(channels, kernel_size=1,
                                       strides=stride, use_bias=False,
                                       in_channels=in_channels,
                                       layout=layout))
            self.downsample.add(_bn(layout))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    """Pre-activation residual block (reference: resnet.py:137)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self.bn1 = _bn(layout)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = _bn(layout)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = Conv2D(channels, 1, stride, use_bias=False,
                                     in_channels=in_channels, layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    """Pre-activation bottleneck (reference: resnet.py:188)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self.bn1 = _bn(layout)
        self.conv1 = Conv2D(channels // 4, kernel_size=1, strides=1,
                            use_bias=False, layout=layout)
        self.bn2 = _bn(layout)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = _bn(layout)
        self.conv3 = Conv2D(channels, kernel_size=1, strides=1,
                            use_bias=False, layout=layout)
        if downsample:
            self.downsample = Conv2D(channels, 1, stride, use_bias=False,
                                     in_channels=in_channels, layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """ResNet V1 (reference: resnet.py:246)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", stem_s2d=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        assert not (stem_s2d and layout != "NHWC"), \
            "stem_s2d requires layout='NHWC'"
        self._layout = layout
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                if stem_s2d:
                    self.features.add(_S2DStemConv(channels[0]))
                else:
                    self.features.add(Conv2D(channels[0], 7, 2, 3,
                                             use_bias=False, layout=layout))
                self.features.add(_bn(layout))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1, layout=layout))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i], layout=layout))
            self.features.add(GlobalAvgPool2D(layout=layout))
            self.output = Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW"):
        layer = HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout,
                            prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    """ResNet V2 (reference: resnet.py:303)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", stem_s2d=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        assert not (stem_s2d and layout != "NHWC"), \
            "stem_s2d requires layout='NHWC'"
        self._layout = layout
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            self.features.add(_bn(layout, scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                if stem_s2d:
                    self.features.add(_S2DStemConv(channels[0]))
                else:
                    self.features.add(Conv2D(channels[0], 7, 2, 3,
                                             use_bias=False, layout=layout))
                self.features.add(_bn(layout))
                self.features.add(Activation("relu"))
                self.features.add(MaxPool2D(3, 2, 1, layout=layout))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels, layout=layout))
                in_channels = channels[i + 1]
            self.features.add(_bn(layout))
            self.features.add(Activation("relu"))
            self.features.add(GlobalAvgPool2D(layout=layout))
            self.output = Dense(classes, in_units=in_channels)

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, layout="NCHW"):
        layer = HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, layout=layout,
                            prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                layout=layout, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


# spec table (reference: resnet.py:365)
resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    """Construct a ResNet (reference: resnet.py:386). ``pretrained`` loads
    from a local file path in kwargs['params_file'] (no network egress)."""
    assert num_layers in resnet_spec, \
        f"Invalid number of layers: {num_layers}. " \
        f"Options are {str(resnet_spec.keys())}"
    block_type, layers, channels = resnet_spec[num_layers]
    assert 1 <= version <= 2, \
        f"Invalid resnet version: {version}. Options are 1 and 2."
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    from ._common import load_pretrained
    pf = kwargs.pop("params_file", None)
    net = resnet_class(block_class, layers, channels, **kwargs)
    return load_pretrained(net, pretrained, pf, ctx)


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
