"""Model zoo (reference: python/mxnet/gluon/model_zoo/)."""
from . import vision  # noqa: F401
from . import ssd  # noqa: F401
from .vision import get_model  # noqa: F401
from .ssd import ssd_300_vgg16_reduced, MultiBoxLoss, SSD  # noqa: F401
