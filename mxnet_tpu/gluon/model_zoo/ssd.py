"""SSD: Single Shot MultiBox Detector (VGG16-reduced, 300x300).

Reference: example/ssd/symbol/symbol_builder.py (get_symbol_train /
get_symbol), example/ssd/symbol/vgg16_reduced.py (the backbone with
dilated fc6/fc7 convs), example/ssd/train/train_net.py (loss wiring).
The north-star BASELINE.md names "SSD-300 VGG16" as a required config.

TPU-first notes: the whole detector — backbone, heads, anchor
generation — is one HybridBlock, so under hybridize it compiles to a
single XLA program; anchors are constants folded at trace time. The
MultiBox* ops it drives are the fixed-shape mask-based kernels in
ops/contrib_det.py.
"""
from __future__ import annotations


from ..block import HybridBlock
from ..loss import Loss
from .. import nn

__all__ = ["SSD", "MultiBoxLoss", "ssd_300_vgg16_reduced", "vgg16_reduced"]


class _L2NormScale(HybridBlock):
    """Channel-wise L2 normalization with a learned per-channel scale
    (reference: symbol_builder.py uses L2Normalization mode='channel'
    with an init-20 scale on relu4_3)."""

    def __init__(self, n_channel, initial=20.0, **kwargs):
        super().__init__(**kwargs)
        from ...initializer import Constant
        with self.name_scope():
            self.scale = self.params.get(
                "scale", shape=(1, n_channel, 1, 1),
                init=Constant(initial))

    def hybrid_forward(self, F, x, scale=None):
        return F.L2Normalization(x, mode="channel") * scale


def vgg16_reduced():
    """VGG16 with pool5 3x3/1 and dilated fc6/fc7 convs
    (reference: example/ssd/symbol/vgg16_reduced.py). Returns the list of
    stages; stage outputs feed the SSD heads."""
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512)]
    up_to_relu43 = nn.HybridSequential(prefix="")
    for i, (n, ch) in enumerate(cfg):
        for _ in range(n):
            up_to_relu43.add(nn.Conv2D(ch, 3, padding=1,
                                       activation="relu"))
        if i < len(cfg) - 1:
            # caffe-style ceil pooling: 300 -> 150 -> 75 -> 38 (the SSD-300
            # anchor ledger depends on the 38x38 first feature map)
            up_to_relu43.add(nn.MaxPool2D(2, 2, ceil_mode=True))

    rest = nn.HybridSequential(prefix="")
    rest.add(nn.MaxPool2D(2, 2, ceil_mode=True))
    for _ in range(3):
        rest.add(nn.Conv2D(512, 3, padding=1, activation="relu"))
    rest.add(nn.MaxPool2D(3, 1, 1))  # pool5: 3x3 stride 1
    # fc6: dilated 3x3, fc7: 1x1 (the "reduced" fully-conv fc layers)
    rest.add(nn.Conv2D(1024, 3, padding=6, dilation=6, activation="relu"))
    rest.add(nn.Conv2D(1024, 1, activation="relu"))
    return up_to_relu43, rest


def _extra_layers(spec):
    """Extra feature stages appended after the backbone
    (reference: symbol_builder.py multi_layer_feature)."""
    stages = []
    for mid, out, stride, pad in spec:
        s = nn.HybridSequential(prefix="")
        s.add(nn.Conv2D(mid, 1, activation="relu"))
        s.add(nn.Conv2D(out, 3, strides=stride, padding=pad,
                        activation="relu"))
        stages.append(s)
    return stages


class SSD(HybridBlock):
    """Generic SSD detector.

    stages: list of HybridSequential feature stages applied in sequence;
    the output of each (from the first onwards) feeds a detection head.
    sizes/ratios: per-stage anchor parameters (MultiBoxPrior convention).
    Returns (cls_preds (N, C+1, A), loc_preds (N, A*4), anchors (1, A, 4)).
    """

    def __init__(self, stages, sizes, ratios, steps, classes,
                 l2_norm_channels=None, **kwargs):
        super().__init__(**kwargs)
        assert len(stages) == len(sizes) == len(ratios) == len(steps)
        self._num_classes = classes
        self._sizes = sizes
        self._ratios = ratios
        self._steps = steps
        with self.name_scope():
            self.stages = nn.HybridSequential(prefix="stages_")
            for s in stages:
                self.stages.add(s)
            self.norm = (_L2NormScale(l2_norm_channels, prefix="l2norm_")
                         if l2_norm_channels else None)
            self.cls_heads = nn.HybridSequential(prefix="cls_")
            self.loc_heads = nn.HybridSequential(prefix="loc_")
            for sz, rt in zip(sizes, ratios):
                k = len(sz) + len(rt) - 1
                self.cls_heads.add(nn.Conv2D(k * (classes + 1), 3,
                                             padding=1))
                self.loc_heads.add(nn.Conv2D(k * 4, 3, padding=1))

    def forward(self, x):
        from ... import ndarray as F
        cls_preds, loc_preds, anchors = [], [], []
        feat = x
        for i, stage in enumerate(self.stages):
            feat = stage(feat)
            f = self.norm(feat) if (i == 0 and self.norm is not None) \
                else feat
            c = self.cls_heads[i](f)
            l = self.loc_heads[i](f)
            n = c.shape[0]
            # (N, K*(C+1), H, W) -> (N, H*W*K, C+1)
            c = c.transpose((0, 2, 3, 1)).reshape(
                (n, -1, self._num_classes + 1))
            l = l.transpose((0, 2, 3, 1)).reshape((n, -1))
            cls_preds.append(c)
            loc_preds.append(l)
            anchors.append(F._contrib_MultiBoxPrior(
                f, sizes=self._sizes[i], ratios=self._ratios[i],
                steps=(self._steps[i], self._steps[i]), clip=False))
        cls_concat = F.concat(*cls_preds, dim=1).transpose((0, 2, 1))
        loc_concat = F.concat(*loc_preds, dim=1)
        anc_concat = F.concat(*anchors, dim=1)
        return cls_concat, loc_concat, anc_concat

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise RuntimeError("SSD uses forward()")

    def detect(self, x, nms_threshold=0.45, threshold=0.01, nms_topk=400):
        """Full inference: forward + softmax + decode + NMS ->
        (N, A, 6) rows [cls_id, score, x1, y1, x2, y2]."""
        from ... import ndarray as F
        cls_preds, loc_preds, anchors = self(x)
        probs = F.softmax(cls_preds, axis=1)
        return F._contrib_MultiBoxDetection(
            probs, loc_preds, anchors, nms_threshold=nms_threshold,
            threshold=threshold, nms_topk=nms_topk)


class MultiBoxLoss(Loss):
    """SSD training loss (reference: example/ssd/symbol/symbol_builder.py
    get_symbol_train: SoftmaxOutput w/ ignore + smooth_l1 * loc_mask,
    negative mining 3:1).

    __call__(cls_preds (N, C+1, A), loc_preds (N, A*4), label (N, G, 6),
    anchors (1, A, 4)) -> scalar loss per batch element (N,).
    """

    def __init__(self, negative_mining_ratio=3.0, lambd=1.0,
                 overlap_threshold=0.5, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._ratio = negative_mining_ratio
        self._lambd = lambd
        self._thresh = overlap_threshold

    def hybrid_forward(self, F, cls_preds, loc_preds, label, anchors):
        loc_t, loc_m, cls_t = F._contrib_MultiBoxTarget(
            anchors, label, cls_preds,
            overlap_threshold=self._thresh,
            negative_mining_ratio=self._ratio,
            negative_mining_thresh=0.5)
        # classification: softmax CE over (N, C+1, A), ignore cls_t == -1
        logits = cls_preds.transpose((0, 2, 1))          # (N, A, C+1)
        logp = F.log_softmax(logits, axis=-1)
        tgt = F.maximum(cls_t, F.zeros_like(cls_t))
        picked = -F.pick(logp, tgt, axis=-1)             # (N, A)
        keep = cls_t >= 0
        cls_loss = (picked * keep).sum(axis=-1) / \
            F.maximum(keep.sum(axis=-1), F.ones_like(keep.sum(axis=-1)))
        # localization: smooth L1 on positives
        loc_loss = (F.smooth_l1(loc_preds - loc_t, scalar=1.0) *
                    loc_m).sum(axis=-1) / \
            F.maximum(loc_m.sum(axis=-1),
                      F.ones_like(loc_m.sum(axis=-1)))
        return cls_loss + self._lambd * loc_loss


def ssd_300_vgg16_reduced(classes=20, **kwargs):
    """SSD-300 with VGG16-reduced backbone (the BASELINE.md config;
    reference: example/ssd/symbol/symbol_builder.py + vgg16_reduced.py).
    Anchor sizes/ratios/steps follow the reference's train_net defaults.
    """
    base43, base7 = vgg16_reduced()
    extras = _extra_layers([(256, 512, 2, 1), (128, 256, 2, 1),
                            (128, 256, 1, 0), (128, 256, 1, 0)])
    stages = [base43, base7] + extras
    sizes = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
             (0.71, 0.79), (0.88, 0.961)]
    ratios = [(1.0, 2.0, 0.5)] + [(1.0, 2.0, 0.5, 3.0, 1.0 / 3)] * 3 + \
        [(1.0, 2.0, 0.5)] * 2
    steps = [8 / 300, 16 / 300, 32 / 300, 64 / 300, 100 / 300, 1.0]
    return SSD(stages, sizes, ratios, steps, classes,
               l2_norm_channels=512, **kwargs)
