"""BERT model family (the "BERT-base (fused attention + AMP)" north-star
config, BASELINE.md).

The reference repo carries BERT only as example/gluon-nlp-adjacent code;
here it is a first-class model-zoo entry built on the TPU-native fused
attention (gluon/nn/attention.py -> Pallas flash kernel). Architecture
follows the standard BERT-base recipe: learned token/segment/position
embeddings, post-LN transformer encoder, GELU FFN, tanh pooler.
"""
from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from ..nn import (Dense, Dropout, Embedding, LayerNorm, HybridSequential,
                  Activation)
from ..nn.attention import MultiHeadAttention

__all__ = ["BERTEncoderLayer", "BERTEncoder", "BERTModel", "bert_base",
           "bert_small", "get_bert"]


class BERTEncoderLayer(HybridBlock):
    """One post-LN transformer encoder layer."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 flash=True, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout,
                                                flash=flash,
                                                prefix="attn_")
            self.attn_ln = LayerNorm(prefix="attn_ln_")
            self.ffn1 = Dense(hidden_size, flatten=False, prefix="ffn1_")
            self.ffn_act = Activation("gelu", prefix="gelu_")
            self.ffn2 = Dense(units, flatten=False, prefix="ffn2_")
            self.ffn_ln = LayerNorm(prefix="ffn_ln_")
            self.dropout_layer = Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        att = self.attention(x, None, None, mask)
        x = self.attn_ln(x + att)
        h = self.ffn2(self.ffn_act(self.ffn1(x)))
        if self.dropout_layer is not None:
            h = self.dropout_layer(h)
        return self.ffn_ln(x + h)

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise RuntimeError("BERTEncoderLayer dispatches in forward()")


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.1, flash=True, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                layer = BERTEncoderLayer(units, hidden_size, num_heads,
                                         dropout=dropout, flash=flash,
                                         prefix=f"layer{i}_")
                self.register_child(layer, f"layer{i}")
                self.layers.append(layer)

    def forward(self, x, mask=None):
        for layer in self.layers:
            x = layer(x, mask)
        return x

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise RuntimeError("BERTEncoder dispatches in forward()")


class BERTModel(HybridBlock):
    """BERT encoder with embeddings and pooler.

    forward(token_ids (B, T), token_types (B, T) | None,
            valid_length (B,) | None) -> (sequence (B, T, U), pooled (B, U))
    """

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, flash=True, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units,
                                        prefix="word_embed_")
            self.token_type_embed = Embedding(type_vocab_size, units,
                                              prefix="type_embed_")
            self.position_weight = self.params.get(
                "position_embed", shape=(max_length, units))
            self.embed_ln = LayerNorm(prefix="embed_ln_")
            self.embed_dropout = Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout=dropout,
                                       flash=flash, prefix="enc_")
            self.pooler = Dense(units, activation="tanh", flatten=False,
                                prefix="pooler_")

    def forward(self, inputs, token_types=None, valid_length=None):
        from ... import ndarray as F
        b, t = inputs.shape
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        pos = self.position_weight.data()[:t]
        x = x + pos.reshape((1, t, self._units))
        x = self.embed_ln(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        mask = None
        if valid_length is not None:
            # additive padding row (B, T): 0 for valid, -1e30 for padding
            arange = F.arange(0, t).reshape((1, t))
            mask = (arange.broadcast_to((b, t)) <
                    valid_length.reshape((-1, 1)).broadcast_to((b, t)))
            mask = (1.0 - mask) * -1e30
        seq = self.encoder(x, mask)
        pooled = self.pooler(seq[:, 0, :])
        return seq, pooled

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise RuntimeError("BERTModel dispatches in forward()")


_BERT_CONFIGS = {
    # name: (num_layers, units, hidden, heads)
    "bert_base": (12, 768, 3072, 12),
    "bert_large": (24, 1024, 4096, 16),
    "bert_small": (4, 128, 512, 4),
}


def get_bert(name, vocab_size=30522, **kwargs):
    layers, units, hidden, heads = _BERT_CONFIGS[name]
    return BERTModel(vocab_size=vocab_size, units=units,
                     hidden_size=hidden, num_layers=layers,
                     num_heads=heads, **kwargs)


def bert_base(**kwargs):
    """BERT-base: 12 layers, 768 units, 12 heads (north-star config)."""
    return get_bert("bert_base", **kwargs)


def bert_small(**kwargs):
    """Small BERT for tests/CI."""
    return get_bert("bert_small", **kwargs)
