"""DevicePrefetchIter: background host→device staging, N batches deep.

The reference hides host-side input latency with the C++ prefetcher
decorator (src/io/iter_prefetcher.h) feeding pinned staging buffers. The
TPU-native analogue: a background thread that pulls batches from any
iterable and runs ``jax.device_put`` on them ahead of the consumer, with a
bounded queue providing N-deep double buffering — the next batch's H2D
copy (and host batchify work) overlaps the current step's compute instead
of serializing in front of it. PR 3's StepTimer ``data_fraction`` gauge is
the before/after meter.

Opt-in everywhere it is wired (``DataLoader(device_prefetch=...)``,
``io.PrefetchingIter(device_prefetch=True)``, the estimator), with
``MXNET_TPU_DATA_PREFETCH=<depth>`` as the ambient default.

Ordering and error transparency are contractual: batches come out in
exactly the source order, and an exception raised by the source surfaces
in the consumer at the position it occurred.
"""
from __future__ import annotations

import os
import queue
import threading
import time

from ...ndarray import NDArray

__all__ = ["DevicePrefetchIter", "stage_batch", "default_prefetch_depth"]

_DONE = object()


def default_prefetch_depth():
    """Ambient device-prefetch depth: MXNET_TPU_DATA_PREFETCH (batches),
    0/unset = off."""
    try:
        return max(0, int(os.environ.get("MXNET_TPU_DATA_PREFETCH", "0")
                          or 0))
    except ValueError:
        return 0


def _resolve_device(ctx):
    if ctx is None:
        return None
    if hasattr(ctx, "jax_device"):     # mxnet_tpu Context
        return ctx.jax_device
    return ctx                          # already a jax.Device


def stage_batch(batch, device=None):
    """Recursively ``device_put`` the NDArray / jax-array leaves of a
    batch structure (list/tuple/dict/DataBatch). Other leaf types (numpy,
    scalars, strings) pass through untouched — staging must not change
    what the consumer receives, only where the arrays live."""
    import jax
    if isinstance(batch, NDArray):
        from ...ndarray.sparse import BaseSparseNDArray
        if isinstance(batch, BaseSparseNDArray):
            # pass through untouched: reading ._data would densify the
            # batch, defeating sparse pipelines downstream
            return batch
        return NDArray(jax.device_put(batch._data, device))
    if isinstance(batch, jax.Array):
        return jax.device_put(batch, device)
    if isinstance(batch, (list, tuple)):
        return type(batch)(stage_batch(b, device) for b in batch)
    if isinstance(batch, dict):
        return {k: stage_batch(v, device) for k, v in batch.items()}
    data = getattr(batch, "data", None)
    label = getattr(batch, "label", None)
    if isinstance(data, (list, tuple)):
        # io.DataBatch-shaped object: stage its payloads in place
        # (label may be None — inference batches — or a tuple)
        batch.data = [stage_batch(d, device) for d in data]
        if isinstance(label, (list, tuple)):
            batch.label = [stage_batch(l, device) for l in label]
        return batch
    return batch


def _metrics():
    from ...observability import get_registry
    reg = get_registry()
    return {
        "batches": reg.counter(
            "mxtpu_data_prefetch_batches_total",
            "Batches staged onto device by a prefetch thread."),
        "depth": reg.gauge(
            "mxtpu_data_prefetch_depth",
            "Configured double-buffer depth of the newest prefetcher."),
        "fill": reg.gauge(
            "mxtpu_data_prefetch_queue_fill",
            "Staged batches waiting at the last consumer read (0 = the "
            "consumer is data-bound, depth = fully hidden)."),
        "wait": reg.histogram(
            "mxtpu_data_prefetch_wait_seconds",
            "Consumer time blocked waiting for a staged batch."),
    }


class DevicePrefetchIter:
    """Wrap any batch iterable with background device staging.

    Parameters
    ----------
    source : iterable of batches (re-iterable sources give a fresh
        producer thread per ``__iter__``)
    depth : queue depth in batches (default: env
        ``MXNET_TPU_DATA_PREFETCH`` or 2)
    ctx : Context / jax.Device to stage onto (default: the arrays'
        default placement)
    stage : False turns this into a plain host-side prefetch thread
        (batches are queued as produced, no device_put) — what
        ``DataLoader(prefetch=N, num_workers=0)`` uses.
    """

    def __init__(self, source, depth=None, ctx=None, stage=True):
        if depth is None:
            depth = default_prefetch_depth() or 2
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = source
        self._depth = depth
        self._device = _resolve_device(ctx)
        self._stage = stage
        # the mxtpu_data_prefetch_* series mean DEVICE staging; a plain
        # host-side prefetch thread (stage=False) must not feed them
        self._obs = _metrics() if stage else None
        if self._obs is not None:
            self._obs["depth"].set(depth)

    def __iter__(self):
        q = queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        src = iter(self._source)
        device, do_stage, obs = self._device, self._stage, self._obs
        from ...observability.tracing import get_tracer
        tracer = get_tracer()
        # the consumer's current span, captured at iteration start: the
        # staging worker's spans parent under it so an exported trace
        # shows H2D staging hanging off the training loop that asked
        # for it (contextvars do not cross threads on their own)
        parent = tracer.current()

        def producer():
            try:
                for item in src:
                    if do_stage:
                        with tracer.span("mxtpu.data_prefetch.stage",
                                         "data", parent):
                            item = stage_batch(item, device)
                        obs["batches"].inc()  # obs present when staging
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                item = _DONE
            except BaseException as e:  # surfaced in the consumer
                item = e
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        worker = threading.Thread(target=producer, daemon=True,
                                  name="mxtpu-device-prefetch")
        worker.start()
        try:
            while True:
                t0 = time.monotonic()
                item = q.get()
                if obs is not None:
                    obs["wait"].observe(time.monotonic() - t0)
                    obs["fill"].set(q.qsize())
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # consumer abandoned the iterator (break / exception / GC):
            # unblock and retire the producer
            stop.set()

    def __len__(self):
        return len(self._source)
