"""DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py:121-234 — fork-based
worker pool with shared-memory NDArray pickling feeding the GPUs. TPU-native
redesign: batches are assembled on host numpy (cheap) and land on device as
one ``jax.device_put`` per batch; the multiprocessing path uses Python's
``multiprocessing.Pool`` with numpy arrays over pipes (no custom shared-mem
NDArray rebuild needed, since device transfer happens in the consumer
process — PJRT owns pinned staging).

``num_workers>0`` parallelizes the *decode/augment* stage, which is where
the reference spent its worker time too.
"""
from __future__ import annotations

import multiprocessing
import sys

import numpy as _np

from ...ndarray import NDArray, array as nd_array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py:127)."""
    if isinstance(data[0], NDArray):
        return nd_array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    arr = _np.asarray(data)
    return nd_array(arr)


# with no shared-mem rebuild needed, the mp variant is the same fn
default_mp_batchify_fn = default_batchify_fn


def _as_numpy_sample(sample):
    if isinstance(sample, NDArray):
        return sample.asnumpy()
    if isinstance(sample, tuple):
        return tuple(_as_numpy_sample(s) for s in sample)
    return sample


class _WorkerInitializer:
    """Picklable initializer exposing the dataset to pool workers.

    The class attribute is per-*process* state: safe for process pools
    (each worker process holds its own copy) — NOT used for thread pools,
    which would share it across loaders; those use ``_ThreadFetcher``."""
    _dataset = None

    @staticmethod
    def init(dataset):
        _WorkerInitializer._dataset = dataset


def _worker_fetch(indices):
    ds = _WorkerInitializer._dataset
    return [_as_numpy_sample(ds[i]) for i in indices]


class _ThreadFetcher:
    """Per-loader fetcher for thread pools (threads share the instance)."""

    def __init__(self, dataset):
        self._dataset = dataset

    def __call__(self, indices):
        return [_as_numpy_sample(self._dataset[i]) for i in indices]


class DataLoader:
    """Mini-batch iterator over a Dataset (reference: dataloader.py:443).

    ``prefetch`` counts batches fetched ahead of the consumer: with
    ``num_workers>0`` it bounds the in-flight pool requests (default
    ``2*num_workers``); with ``num_workers=0`` an explicit value spins a
    background thread that batchifies ahead (default 0 = fully
    synchronous). ``device_prefetch`` additionally stages ready batches
    onto the device from a background thread, ``device_prefetch`` deep,
    so the next batch's H2D copy overlaps the current step's compute —
    defaults to ``MXNET_TPU_DATA_PREFETCH`` (0 = off).
    """

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 pin_device_id=0, prefetch=None, thread_pool=False,
                 timeout=120, device_prefetch=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        # an explicit prefetch= must win even when num_workers=0 (it used
        # to be silently zeroed by the `or` default in that case)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        from .prefetch import default_prefetch_depth
        self._device_prefetch = max(0, device_prefetch
                                    if device_prefetch is not None
                                    else default_prefetch_depth())
        self._pool = None
        self._fetch = _ThreadFetcher(self._dataset)
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.dummy import Pool as ThreadPool
                self._pool = ThreadPool(self._num_workers)
            else:
                # Process-pool start method, in preference order:
                # - forkserver: the server process is started clean and
                #   children fork from IT, so (a) no fork of the
                #   multithreaded JAX/PJRT parent (deadlock risk) and
                #   (b) unlike spawn, an unguarded user __main__ script
                #   is NOT re-executed in every worker — the classic
                #   spawn footgun.
                # - spawn: same safety w.r.t. the parent, but scripts
                #   without `if __name__ == "__main__":` re-run in each
                #   worker.
                # - thread pool: when the dataset cannot cross a process
                #   boundary at all (decode/augment in numpy/cv2
                #   releases the GIL anyway).
                methods = [m for m in ("forkserver", "spawn")
                           if m in multiprocessing.get_all_start_methods()]
                err = None
                for method in methods:
                    try:
                        ctx = multiprocessing.get_context(method)
                        self._pool = ctx.Pool(
                            self._num_workers,
                            initializer=_WorkerInitializer.init,
                            initargs=(self._dataset,))
                        self._fetch = _worker_fetch
                        break
                    except Exception as e:
                        err = e
                        self._pool = None
                if self._pool is None:
                    import warnings
                    warnings.warn(
                        "dataset cannot be sent to worker processes "
                        f"({err!r}); DataLoader falls back to a thread "
                        "pool", stacklevel=2)
                    from multiprocessing.dummy import Pool as ThreadPool
                    self._pool = ThreadPool(self._num_workers)

    def __iter__(self):
        batches = self._iter_batches()
        if self._device_prefetch > 0:
            from .prefetch import DevicePrefetchIter
            batches = iter(DevicePrefetchIter(
                batches, depth=self._device_prefetch))
        elif self._pool is None and self._prefetch > 0:
            # single-process path: honor an explicit prefetch= request
            # with a host-side batchify-ahead thread (no device staging)
            from .prefetch import DevicePrefetchIter
            batches = iter(DevicePrefetchIter(
                batches, depth=self._prefetch, stage=False))
        yield from batches

    def _iter_batches(self):
        if self._pool is None:
            for batch_idx in self._batch_sampler:
                yield self._batchify_fn(
                    [self._dataset[i] for i in batch_idx])
            return
        # async prefetch pipeline over the worker pool (reference
        # prefetcher: iter_prefetcher.h / dataloader _MultiWorkerIter)
        batches = iter(self._batch_sampler)
        inflight = []
        for _ in range(max(1, self._prefetch)):
            idx = next(batches, None)
            if idx is None:
                break
            inflight.append(self._pool.apply_async(self._fetch, (idx,)))
        while inflight:
            res = inflight.pop(0)
            samples = res.get(self._timeout)
            idx = next(batches, None)
            if idx is not None:
                inflight.append(self._pool.apply_async(self._fetch, (idx,)))
            yield self._batchify_fn(samples)

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        # tolerate partially-constructed instances and interpreter
        # shutdown (modules may already be torn down)
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass
