"""Gluon data API (reference: python/mxnet/gluon/data/)."""
from .dataset import *  # noqa: F401,F403
from .sampler import *  # noqa: F401,F403
from .dataloader import *  # noqa: F401,F403
from .prefetch import DevicePrefetchIter, stage_batch  # noqa: F401
from . import vision  # noqa: F401
