"""Vision transforms.

Reference: python/mxnet/gluon/data/vision/transforms.py (Compose, Cast,
ToTensor, Normalize, RandomResizedCrop, CenterCrop, Resize, flips, color
jitter). Transforms run on host numpy inside DataLoader workers (decode/
augment is CPU work in the reference too); the assembled batch lands on
device once.
"""
from __future__ import annotations

import numpy as _np

from ....ndarray import NDArray, array as nd_array
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomCrop",
           "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation", "RandomHue",
           "RandomLighting", "RandomColorJitter"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class Compose(Sequential):
    """Sequential transform composition (reference: transforms.py:37)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)

    def __call__(self, x, *args):
        for t in self._children.values():
            x = t(x)
        return (x,) + args if args else x

    def forward(self, x):
        return self.__call__(x)


class Cast(Block):
    """dtype cast (reference: transforms.py:110)."""

    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype) if isinstance(x, NDArray) else \
            nd_array(_to_np(x).astype(self._dtype))


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1]
    (reference: transforms.py:138)."""

    def forward(self, x):
        a = _to_np(x).astype(_np.float32) / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return nd_array(a)


class Normalize(Block):
    """(x - mean) / std per channel (reference: transforms.py:182)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype=_np.float32)
        self._std = _np.asarray(std, dtype=_np.float32)

    def forward(self, x):
        a = _to_np(x).astype(_np.float32)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd_array((a - mean) / std)


def _resize_np(a, size, interp="bilinear"):
    import jax
    import jax.numpy as jnp
    h, w = size if isinstance(size, (tuple, list)) else (size, size)
    method = "linear" if interp in ("bilinear", 1) else "nearest"
    out_shape = (h, w, a.shape[2]) if a.ndim == 3 else (h, w)
    return _np.asarray(jax.image.resize(jnp.asarray(a, jnp.float32),
                                        out_shape, method=method))


class Resize(Block):
    """Resize to (w,h) (reference: transforms.py:279)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        a = _to_np(x)
        if isinstance(self._size, int):
            if self._keep:
                h, w = a.shape[:2]
                if h < w:
                    size = (self._size, int(w * self._size / h))
                else:
                    size = (int(h * self._size / w), self._size)
            else:
                size = (self._size, self._size)
        else:
            size = (self._size[1], self._size[0])  # reference takes (w,h)
        return nd_array(_resize_np(a, size, self._interpolation))


def _crop(a, y, x, h, w):
    return a[y:y + h, x:x + w]


class CenterCrop(Block):
    """Center crop (reference: transforms.py:345)."""

    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else \
            (size[1], size[0])
        self._interpolation = interpolation

    def forward(self, x):
        a = _to_np(x)
        ch, cw = self._size
        h, w = a.shape[:2]
        if h < ch or w < cw:
            a = _resize_np(a, (max(h, ch), max(w, cw)), self._interpolation)
            h, w = a.shape[:2]
        y0 = (h - ch) // 2
        x0 = (w - cw) // 2
        return nd_array(_crop(a, y0, x0, ch, cw))


class RandomCrop(Block):
    """Random crop w/ optional padding."""

    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else \
            (size[1], size[0])
        self._pad = pad
        self._interpolation = interpolation

    def forward(self, x):
        a = _to_np(x)
        if self._pad:
            p = self._pad
            a = _np.pad(a, ((p, p), (p, p)) + ((0, 0),) * (a.ndim - 2),
                        mode="constant")
        ch, cw = self._size
        h, w = a.shape[:2]
        if h < ch or w < cw:
            a = _resize_np(a, (max(h, ch), max(w, cw)), self._interpolation)
            h, w = a.shape[:2]
        y0 = _np.random.randint(0, h - ch + 1)
        x0 = _np.random.randint(0, w - cw + 1)
        return nd_array(_crop(a, y0, x0, ch, cw))


class RandomResizedCrop(Block):
    """Random area/aspect crop then resize (reference: transforms.py:383)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else \
            (size[1], size[0])
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        a = _to_np(x)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            ch = int(round(_np.sqrt(target_area / aspect)))
            cw = int(round(_np.sqrt(target_area * aspect)))
            if ch <= h and cw <= w:
                y0 = _np.random.randint(0, h - ch + 1)
                x0 = _np.random.randint(0, w - cw + 1)
                return nd_array(_resize_np(_crop(a, y0, x0, ch, cw),
                                           self._size,
                                           self._interpolation))
        return CenterCrop(self._size, self._interpolation)(nd_array(a))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        a = _to_np(x)
        if _np.random.rand() < 0.5:
            a = a[:, ::-1].copy()
        return nd_array(a)


class RandomFlipTopBottom(Block):
    def forward(self, x):
        a = _to_np(x)
        if _np.random.rand() < 0.5:
            a = a[::-1].copy()
        return nd_array(a)


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        a = _to_np(x).astype(_np.float32)
        f = 1.0 + _np.random.uniform(-self._b, self._b)
        return nd_array(_np.clip(a * f, 0, 255))


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        a = _to_np(x).astype(_np.float32)
        f = 1.0 + _np.random.uniform(-self._c, self._c)
        gray = a.mean()
        return nd_array(_np.clip(gray + (a - gray) * f, 0, 255))


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        a = _to_np(x).astype(_np.float32)
        f = 1.0 + _np.random.uniform(-self._s, self._s)
        gray = a.mean(axis=-1, keepdims=True)
        return nd_array(_np.clip(gray + (a - gray) * f, 0, 255))


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference: transforms.py:780)."""

    _eigval = _np.array([55.46, 4.794, 1.148])
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]])

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = _to_np(x).astype(_np.float32)
        alpha = _np.random.normal(0, self._alpha, size=(3,))
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd_array(_np.clip(a + rgb, 0, 255))


class RandomHue(Block):
    """Hue jitter via YIQ-space rotation (reference: transforms.py
    RandomHue → image.RandomHueAug)."""

    _to_yiq = _np.array([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]])
    _from_yiq = _np.linalg.inv(_to_yiq)

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        a = _to_np(x).astype(_np.float32)
        theta = _np.random.uniform(-self._h, self._h) * _np.pi
        c, s = _np.cos(theta), _np.sin(theta)
        rot = _np.array([[1, 0, 0], [0, c, -s], [0, s, c]])
        m = self._from_yiq @ rot @ self._to_yiq
        return nd_array(_np.clip(a @ m.T, 0, 255))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = _np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x
