"""Vision datasets.

Reference: python/mxnet/gluon/data/vision/datasets.py (MNIST,
FashionMNIST, CIFAR10/100, ImageRecordDataset, ImageFolderDataset). This
environment has no network egress: datasets load from local files when
present (same binary formats as the reference: MNIST idx files, CIFAR
binary batches) and otherwise fall back to a deterministic procedural
surrogate of matching shapes/cardinality so training pipelines and tests
run anywhere (``MXNET_SYNTHETIC_DATA=1`` forces the surrogate).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as _np

from ....ndarray import NDArray, array as nd_array
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synthetic(n, shape, num_classes, seed, template_seed):
    """Deterministic class-separable surrogate data: each class is a fixed
    random template plus noise, so small models reach high accuracy —
    usable for convergence tests like the reference's test_mlp/test_conv.
    ``template_seed`` is shared between train and test splits so a model
    trained on one generalizes to the other."""
    trng = _np.random.RandomState(template_seed)
    templates = trng.uniform(0, 255, size=(num_classes,) + shape)
    rng = _np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(_np.int32)
    noise = rng.normal(0, 32, size=(n,) + shape)
    data = _np.clip(templates[labels] + noise, 0, 255).astype(_np.uint8)
    return data, labels


class _DownloadedDataset(Dataset):
    """Base for file-backed datasets (reference: datasets.py:45)."""

    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(nd_array(self._data[idx]),
                                   self._label[idx])
        return nd_array(self._data[idx]), self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (reference: datasets.py:60). Reads idx-ubyte files
    (train-images-idx3-ubyte[.gz] etc.) if present under root."""

    _shape = (28, 28, 1)
    _num_classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "mnist"),
                 train=True, transform=None):
        self._train = train
        self._train_data = "train-images-idx3-ubyte"
        self._train_label = "train-labels-idx1-ubyte"
        self._test_data = "t10k-images-idx3-ubyte"
        self._test_label = "t10k-labels-idx1-ubyte"
        super().__init__(root, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(dims)

    def _find(self, base):
        for cand in (base, base + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        return None

    def _get_data(self):
        dbase = self._train_data if self._train else self._test_data
        lbase = self._train_label if self._train else self._test_label
        dpath, lpath = self._find(dbase), self._find(lbase)
        if dpath and lpath and not os.environ.get("MXNET_SYNTHETIC_DATA"):
            data = self._read_idx(dpath)
            label = self._read_idx(lpath).astype(_np.int32)
            self._data = data.reshape((-1,) + self._shape)
            self._label = label
        else:
            n = 8192 if self._train else 2048
            self._data, self._label = _synthetic(
                n, self._shape, self._num_classes,
                seed=42 if self._train else 43, template_seed=7)


class FashionMNIST(MNIST):
    """FashionMNIST (reference: datasets.py:118)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 (reference: datasets.py:153). Reads data_batch_*.bin if
    present under root."""

    _shape = (32, 32, 3)
    _num_classes = 10
    _train_files = [f"data_batch_{i}.bin" for i in range(1, 6)]
    _test_files = ["test_batch.bin"]

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            raw = _np.frombuffer(fin.read(), dtype=_np.uint8)
        rec = 1 + self._shape[2] * self._shape[0] * self._shape[1]
        data = raw.reshape(-1, rec)
        return (data[:, 1:].reshape(-1, 3, 32, 32)
                .transpose(0, 2, 3, 1),
                data[:, 0].astype(_np.int32))

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.exists(p) for p in paths) and \
                not os.environ.get("MXNET_SYNTHETIC_DATA"):
            parts = [self._read_batch(p) for p in paths]
            self._data = _np.concatenate([p[0] for p in parts])
            self._label = _np.concatenate([p[1] for p in parts])
        else:
            n = 8192 if self._train else 2048
            self._data, self._label = _synthetic(
                n, self._shape, self._num_classes,
                seed=44 if self._train else 45, template_seed=9)


class CIFAR100(CIFAR10):
    """CIFAR100 (reference: datasets.py:198)."""

    _num_classes = 100
    _train_files = ["train.bin"]
    _test_files = ["test.bin"]

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        super().__init__(root=root, train=train, transform=transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            raw = _np.frombuffer(fin.read(), dtype=_np.uint8)
        rec = 2 + 3 * 32 * 32
        data = raw.reshape(-1, rec)
        return (data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1),
                data[:, 1 if self._fine_label else 0].astype(_np.int32))


class ImageRecordDataset(Dataset):
    """Dataset over an image RecordIO file (reference: datasets.py:243)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = self._record[idx]
        header, img = unpack_img(record, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(nd_array(img), label)
        return nd_array(img), label

    def __len__(self):
        return len(self._record)


class ImageFolderDataset(Dataset):
    """Folder-of-class-folders image dataset (reference: datasets.py:274).
    Decoding uses the io.image codecs (PNG/JPEG via native decoder)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".npy"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = nd_array(_np.load(path))
        else:
            from ....image import imread
            img = imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
