"""Gluon Estimator: train/validate a net with an event-handler loop.

Reference: python/mxnet/gluon/contrib/estimator/estimator.py:42
(Estimator, fit:326, evaluate:272, fit_batch, evaluate_batch,
_prepare_default_handlers). TPU-native notes: one autograd.record()
forward/backward per batch on whatever context the data sits on; the
trainer step itself is the same jit-compiled path Trainer always uses,
so the handler loop adds only Python-level orchestration.
"""
from __future__ import annotations

from ....metric import Accuracy, Loss as LossMetric, EvalMetric
from .... import autograd
from ....ndarray import NDArray
from ... import Trainer
from ...loss import Loss as GluonLoss
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            TrainBegin, TrainEnd, MetricHandler,
                            StoppingHandler, LoggingHandler,
                            GradientUpdateHandler, StepTimerHandler)

__all__ = ["Estimator"]


def _as_nd(x):
    return x if isinstance(x, NDArray) else NDArray(x)


class Estimator:
    """Facilitates training & validation (reference: estimator.py:42).

    Parameters
    ----------
    net : gluon Block (initialized)
    loss : gluon Loss
    train_metrics : EvalMetric or list (default: Accuracy)
    val_metrics : EvalMetric or list (defaults to copies of train)
    trainer : gluon Trainer (default: sgd lr=1e-3)
    """

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None):
        self.net = net
        if not isinstance(loss, GluonLoss):
            raise ValueError("loss must be a gluon Loss")
        self.loss = loss
        self.train_metrics = self._to_list(train_metrics) or [Accuracy()]
        self.val_metrics = self._to_list(val_metrics) or \
            [type(m)() for m in self.train_metrics]
        self.train_loss_metric = LossMetric("train_loss")
        self.val_loss_metric = LossMetric("val_loss")
        self.trainer = trainer if trainer is not None else Trainer(
            net.collect_params(), "sgd", {"learning_rate": 1e-3})
        self.stop_training = False
        self._compiled_step = None
        self._compiled_step_auto = None
        self._step_applied = False

    @staticmethod
    def _to_list(m):
        if m is None:
            return None
        if isinstance(m, EvalMetric):
            return [m]
        return list(m)

    # ------------------------------------------------------------ batch --
    def fit_batch(self, batch):
        """One forward/backward; returns (data, label, pred, loss).
        Override for custom batch semantics (reference: fit_batch).

        With ``fit(compiled_step=...)`` the whole step — forward, loss,
        backward AND the optimizer update — runs as one compiled
        dispatch here; ``GradientUpdateHandler`` then skips its
        ``trainer.step`` for the batch (``_step_applied``)."""
        data, label = _as_nd(batch[0]), _as_nd(batch[1])
        if self._compiled_step is not None:
            out = self._compiled_step(data, label)
            if isinstance(out, tuple):
                # fit(compiled_step=True) convention: loss first, pred
                # rides along as the second program output
                loss, pred = out[0], out[1]
            else:
                # a user-built step whose loss_fn returns only the loss:
                # metric handlers skip pred=None, loss metrics still run
                loss, pred = out, None
            self._step_applied = True
            return data, label, pred, loss
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        return data, label, pred, loss

    def evaluate_batch(self, batch):
        data, label = _as_nd(batch[0]), _as_nd(batch[1])
        pred = self.net(data)
        loss = self.loss(pred, label)
        return data, label, pred, loss

    # ------------------------------------------------------------- eval --
    def evaluate(self, val_data, batch_axis=0):
        """Run validation, updating val metrics (reference:
        evaluate:272)."""
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric.reset()
        with autograd.pause(train_mode=False):
            for batch in val_data:
                _, label, pred, loss = self.evaluate_batch(batch)
                for m in self.val_metrics:
                    m.update(label, pred)
                self.val_loss_metric.update(0, loss)
        return {m.get()[0]: m.get()[1]
                for m in self.val_metrics + [self.val_loss_metric]}

    # -------------------------------------------------------------- fit --
    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None, device_prefetch=None,
            compiled_step=None):
        """Train for ``epochs`` epochs or ``batches`` batches
        (reference: fit:326).

        ``device_prefetch``: batches to stage onto device ahead of the
        step from a background thread (overlapping H2D with compute);
        defaults to ``MXNET_TPU_DATA_PREFETCH`` (0 = off). A source
        that already device-prefetches (e.g. a ``DataLoader`` with the
        same env default) keeps its own depth — the source wins, no
        second staging thread is stacked. The StepTimerHandler's
        ``mxtpu_training_data_fraction`` gauge shows the effect.

        ``compiled_step``: ``True`` compiles the whole training step
        (forward + loss + backward + update) into one buffer-donating
        XLA dispatch per batch via
        ``trainer.compile_step`` (:class:`mxnet_tpu.jit.
        CompiledTrainStep`); pass a pre-built ``CompiledTrainStep`` to
        share programs across fits. Ineligible batches fall back to
        the eager path automatically (see docs/PERFORMANCE.md)."""
        if epochs is None and batches is None:
            epochs = 1
        if compiled_step is True:
            # built once per estimator: net/loss/trainer are fixed at
            # construction, so repeated fits reuse the same programs
            # instead of re-paying the whole-step compile
            if self._compiled_step_auto is None:
                net, loss_obj = self.net, self.loss

                def _loss_and_pred(x, y):
                    pred = net(x)
                    # pred rides along as a program output so the metric
                    # handlers see it without a second forward
                    return loss_obj(pred, y), pred
                self._compiled_step_auto = \
                    self.trainer.compile_step(_loss_and_pred)
            compiled_step = self._compiled_step_auto
        self._compiled_step = compiled_step or None
        handlers = self._prepare_handlers(val_data, epochs, batches,
                                          event_handlers)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize(handlers)

        from ...data.prefetch import (DevicePrefetchIter,
                                      default_prefetch_depth)
        explicit = device_prefetch is not None
        if device_prefetch is None:
            device_prefetch = default_prefetch_depth()
        if device_prefetch and device_prefetch > 0:
            # sources with their own device-prefetch policy (DataLoader)
            # win over the ambient env default — including an explicit
            # opt-out (device_prefetch=0 at the loader) — and an already-
            # active stager is never double-wrapped
            active = isinstance(train_data, DevicePrefetchIter) or \
                getattr(train_data, "_device_prefetch", 0) > 0
            managed = isinstance(train_data, DevicePrefetchIter) or \
                hasattr(train_data, "_device_prefetch")
            if (explicit and not active) or (not explicit and not managed):
                train_data = DevicePrefetchIter(train_data,
                                                depth=device_prefetch)

        from ....observability.tracing import get_tracer
        tracer = get_tracer()
        self.stop_training = False
        for h in train_begin:
            h.train_begin(self)
        epoch = 0
        while not self.stop_training:
            # the epoch span parents everything the epoch causes — the
            # per-batch train_step spans AND the DevicePrefetchIter
            # staging spans on their worker thread (captured context).
            # NOT step-category: the per-batch spans inside it own the
            # device StepTraceAnnotation.
            with tracer.span("mxtpu.estimator.epoch", "epoch", None,
                             {"epoch": epoch}):
                for h in epoch_begin:
                    h.epoch_begin(self)
                for batch in train_data:
                    for h in batch_begin:
                        h.batch_begin(self, batch=batch)
                    data, label, pred, loss = self.fit_batch(batch)
                    for h in batch_end:
                        h.batch_end(self, batch=batch, pred=pred,
                                    label=label, loss=loss)
                    self._sync_stop(handlers)
                    if self.stop_training:
                        break
                for h in epoch_end:
                    h.epoch_end(self)
            epoch += 1
            self._sync_stop(handlers)
        for h in train_end:
            h.train_end(self)

    def _sync_stop(self, handlers):
        if any(getattr(h, "stop_training", False) for h in handlers):
            self.stop_training = True

    def _prepare_handlers(self, val_data, epochs, batches,
                          event_handlers):
        handlers = list(event_handlers or [])
        # defaults mirror _prepare_default_handlers: stopping, gradient
        # update, metrics; logging/validation only when asked for
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, GradientUpdateHandler)
                   for h in handlers):
            handlers.append(GradientUpdateHandler())
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(
                self.train_metrics + [self.train_loss_metric]))
        if not any(isinstance(h, StepTimerHandler) for h in handlers):
            handlers.append(StepTimerHandler())
        from .event_handler import ValidationHandler
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler)
                        for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        key = lambda h: getattr(h, "priority", 0)  # noqa: E731
        return sorted(handlers, key=key)

    def _categorize(self, handlers):
        return ([h for h in handlers if isinstance(h, TrainBegin)],
                [h for h in handlers if isinstance(h, EpochBegin)],
                [h for h in handlers if isinstance(h, BatchBegin)],
                [h for h in handlers if isinstance(h, BatchEnd)],
                [h for h in handlers if isinstance(h, EpochEnd)],
                [h for h in handlers if isinstance(h, TrainEnd)])
