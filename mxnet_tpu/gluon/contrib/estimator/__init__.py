"""Gluon Estimator: high-level fit/evaluate with event handlers.

Reference: python/mxnet/gluon/contrib/estimator/.
"""
from .estimator import Estimator  # noqa: F401
from .event_handler import (  # noqa: F401
    EventHandler, TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
    BatchEnd, StoppingHandler, MetricHandler, ValidationHandler,
    LoggingHandler, CheckpointHandler, EarlyStoppingHandler,
    GradientUpdateHandler)
