"""Estimator event handlers.

Reference: python/mxnet/gluon/contrib/estimator/event_handler.py
(EventHandler:37, StoppingHandler:82, MetricHandler:122,
ValidationHandler:160, LoggingHandler:226, CheckpointHandler:336,
EarlyStoppingHandler, GradientUpdateHandler). Same mixin protocol: a
handler subclasses one or more of the six phase bases and the Estimator
dispatches each phase to every handler that implements it, ordered by
``priority`` (lower runs first) where defined.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as _np

__all__ = ["EventHandler", "TrainBegin", "TrainEnd", "EpochBegin",
           "EpochEnd", "BatchBegin", "BatchEnd", "StoppingHandler",
           "MetricHandler", "ValidationHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler",
           "GradientUpdateHandler", "CheckpointOnPreemption",
           "StepTimerHandler"]


class EventHandler:
    pass


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch epochs or max_batch batches (reference:
    event_handler.py:82)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch is not None and \
                self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch is not None and \
                self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset train metrics each epoch, update them each batch
    (reference: event_handler.py:122)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics or []
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        from ....metric import Loss as _LossMetric
        for m in self.metrics:
            if isinstance(m, _LossMetric):
                if loss is not None:
                    m.update(0, loss)
            elif pred is not None and label is not None:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every ``epoch_period`` epochs / ``batch_period``
    batches (reference: event_handler.py:160)."""

    def __init__(self, val_data, eval_fn, epoch_period=1,
                 batch_period=None, priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period is not None and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period is not None and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                     BatchBegin, BatchEnd):
    """Log training progress (reference: event_handler.py:226).
    ``log_interval`` is 'epoch' or a batch count."""

    def __init__(self, log_interval="epoch", metrics=None,
                 priority=_np.inf):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        t = time.time() - self.train_start
        self.logger.info("Training finished in %.3fs", t)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0
        self.processed_samples = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        batch = kwargs.get("batch")
        if batch is not None:
            try:
                self.processed_samples += len(batch[0])
            except Exception:
                pass
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msg = ", ".join(f"{m.get()[0]}={m.get()[1]:.4f}"
                            for m in self.metrics)
            self.logger.info("[epoch %d batch %d] %s",
                             self.current_epoch, self.batch_index, msg)

    def epoch_end(self, estimator, *args, **kwargs):
        t = time.time() - self.epoch_start
        msg = ", ".join(f"{m.get()[0]}={m.get()[1]:.4f}"
                        for m in self.metrics)
        self.logger.info("[epoch %d] finished in %.3fs: %s",
                         self.current_epoch, t, msg)
        self.current_epoch += 1


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save model+trainer state periodically; optionally only on metric
    improvement (reference: event_handler.py:336)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="auto", epoch_period=1, batch_period=None,
                 max_checkpoints=5, resume_from_checkpoint=False,
                 save_best=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.save_best = save_best
        self.saved = []
        self.current_epoch = 0
        self.current_batch = 0
        if mode == "auto" and monitor is not None:
            name = monitor.get()[0]
            mode = "min" if "loss" in name or "error" in name else "max"
        self._cmp = (lambda a, b: a < b) if mode == "min" else \
            (lambda a, b: a > b)
        self.best = None

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)

    def _save(self, estimator, tag):
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-{tag}.params")
        estimator.net.save_parameters(path)
        if estimator.trainer is not None and \
                hasattr(estimator.trainer, "save_states"):
            try:
                estimator.trainer.save_states(path + ".states")
            except Exception:
                pass
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            for f in (old, old + ".states"):
                if os.path.exists(f):
                    os.remove(f)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period is not None and \
                self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period is not None and \
                self.current_epoch % self.epoch_period == 0:
            if self.save_best and self.monitor is not None:
                val = self.monitor.get()[1]
                if self.best is None or self._cmp(val, self.best):
                    self.best = val
                    self._save(estimator, "best")
            else:
                self._save(estimator, f"epoch{self.current_epoch}")


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stops improving (reference:
    event_handler.py EarlyStoppingHandler)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        name = monitor.get()[0]
        if mode == "auto":
            mode = "min" if "loss" in name or "error" in name else "max"
        self._mode = mode
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = None
        self.current_epoch = 0
        self.best = self.baseline if self.baseline is not None else (
            _np.inf if self._mode == "min" else -_np.inf)

    def _improved(self, val):
        if self._mode == "min":
            return val < self.best - self.min_delta
        return val > self.best + self.min_delta

    def epoch_end(self, estimator, *args, **kwargs):
        val = self.monitor.get()[1]
        if self._improved(val):
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True
                self.stopped_epoch = self.current_epoch
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch is not None:
            logging.getLogger("mxnet_tpu.estimator").info(
                "Early stop at epoch %d: best %s=%.4f",
                self.stopped_epoch, self.monitor.get()[0], self.best)


class CheckpointOnPreemption(TrainBegin, BatchEnd, TrainEnd):
    """Preemption-aware checkpointing: a SIGTERM/SIGINT during training
    triggers ONE final full-state checkpoint at the next step boundary,
    then stops the training loop cleanly.

    The signal itself only sets a flag (resilience.PreemptionGuard);
    this handler polls it in ``batch_end`` — after the gradient update,
    when params/optimizer state are consistent — writes a
    resilience.checkpoint directory via ``trainer.save_state`` (plus the
    net's parameters for trainers without full-state support), and sets
    ``stop_training``. Resume with ``trainer.restore_state(ckpt_dir)``.

    priority: runs after GradientUpdateHandler (-2000) so the step that
    was in flight when the signal landed is fully applied before the
    save.
    """

    def __init__(self, ckpt_dir, signals=None, priority=-1000):
        from ....resilience import PreemptionGuard
        self.ckpt_dir = ckpt_dir
        self.priority = priority
        kwargs = {} if signals is None else {"signals": signals}
        self.guard = PreemptionGuard(**kwargs)
        self.stop_training = False
        self.current_batch = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.stop_training = False
        self.current_batch = 0
        self.guard.install()

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if not self.guard.requested or self.stop_training:
            return
        self.logger.warning(
            "Preemption signal %s received: checkpointing to %s and "
            "stopping", self.guard.signum, self.ckpt_dir)
        self._save(estimator)
        self.stop_training = True

    def train_end(self, estimator, *args, **kwargs):
        self.guard.uninstall()

    def _save(self, estimator):
        trainer = getattr(estimator, "trainer", None)
        if trainer is not None and hasattr(trainer, "save_state"):
            trainer.save_state(self.ckpt_dir)
            # this is the LAST checkpoint of a preempted run — with
            # MXNET_TPU_CKPT_ASYNC the save is in a background writer,
            # and exiting on the atexit flush would reduce a failed
            # write to a warning + exit 0. Join here so a failure
            # raises before the process reports a clean stop.
            if hasattr(trainer, "ckpt_wait"):
                trainer.ckpt_wait()
        else:
            # fall back to params-only via the atomic nd.save path
            os.makedirs(self.ckpt_dir, exist_ok=True)
            estimator.net.save_parameters(
                os.path.join(self.ckpt_dir, "preempt.params"))


class StepTimerHandler(TrainBegin, EpochBegin, BatchBegin, BatchEnd):
    """Step-time telemetry for the estimator loop, driving an
    ``observability.StepTimer``: the gap between one ``batch_end`` and
    the next ``batch_begin`` is input-pipeline wait, ``batch_begin`` to
    ``batch_end`` is compute (forward/backward/metrics + the trainer
    update, which GradientUpdateHandler at priority -2000 runs before
    this handler's batch_end at -100). Added by default in
    ``Estimator.fit`` — metrics cost ~1us/batch and the step-time
    breakdown (``mxtpu_training_step_seconds``,
    ``data_wait_seconds``, ``compute_seconds``,
    ``examples_per_sec``) is the substrate every perf report reads.
    """

    def __init__(self, timer=None, priority=-100):
        self.priority = priority
        self._timer = timer

    @property
    def timer(self):
        if self._timer is None:
            from ....observability import StepTimer
            self._timer = StepTimer()
        return self._timer

    def train_begin(self, estimator, *args, **kwargs):
        self.timer  # create eagerly so fit always registers the series

    def epoch_begin(self, estimator, *args, **kwargs):
        # epoch-end work (validation passes, checkpoints) must not be
        # billed as input-pipeline wait of the next epoch's first step
        self.timer._last_end = None

    def batch_begin(self, estimator, *args, **kwargs):
        self.timer.begin_step()

    def batch_end(self, estimator, *args, **kwargs):
        batch = kwargs.get("batch")
        n = None
        if batch is not None:
            try:
                n = len(batch[0])
            except Exception:
                n = None
        self.timer.end_step(batch_size=n)


class GradientUpdateHandler(BatchEnd):
    """Perform the trainer step after each batch (reference:
    event_handler.py GradientUpdateHandler). Kept as a handler so users
    can reorder/replace the update (e.g. gradient accumulation)."""

    def __init__(self, priority=-2000):
        self.priority = priority

    def batch_end(self, estimator, *args, **kwargs):
        if getattr(estimator, "_step_applied", False):
            # fit_batch ran a CompiledTrainStep: the optimizer update
            # already happened inside the one-dispatch program
            estimator._step_applied = False
            return
        batch = kwargs.get("batch")
        n = len(batch[0]) if batch is not None else 1
        estimator.trainer.step(n)
