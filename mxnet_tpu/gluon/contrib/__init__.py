"""gluon.contrib — contributed gluon components.

Reference: python/mxnet/gluon/contrib/ (estimator, cnn/rnn extras).
"""
from . import estimator  # noqa: F401
from . import nn  # noqa: F401
