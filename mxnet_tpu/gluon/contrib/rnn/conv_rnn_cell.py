"""Convolutional recurrent cells.

Reference: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py
(_BaseConvRNNCell:33, ConvRNNCell/ConvLSTMCell/ConvGRUCell families).
Same contract: ``input_shape`` is the per-step input (C, *spatial);
states are (batch, hidden_channels, *spatial); i2h/h2h are
convolutions (SAME padding derived from the kernel like the
reference's _get_conv_out_size for stride 1).
"""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _BaseConvCell(HybridRecurrentCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, gates, ndim, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)
        self._channels = hidden_channels
        self._ndim = ndim
        self._gates = gates
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, ndim)
        self._h2h_kernel = _tup(h2h_kernel, ndim)
        for k in self._h2h_kernel:
            assert k % 2 == 1, \
                "h2h kernel must be odd for SAME-size states " \
                "(reference conv_rnn_cell.py check)"
        self._i2h_pad = tuple(k // 2 for k in self._i2h_kernel)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        in_c = input_shape[0]
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(gates * hidden_channels, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(gates * hidden_channels,
                       hidden_channels) + self._h2h_kernel,
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(gates * hidden_channels,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(gates * hidden_channels,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        shape = (batch_size, self._channels) + self._input_shape[1:]
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._ndim:]}
                ] * self._n_states

    def _conv_pre(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                  h2h_bias):
        i2h = F.Convolution(x, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, stride=(1,) *
                            self._ndim, pad=self._i2h_pad,
                            num_filter=self._gates * self._channels)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, stride=(1,) *
                            self._ndim, pad=self._h2h_pad,
                            num_filter=self._gates * self._channels)
        return i2h, h2h


class _ConvRNNCell(_BaseConvCell):
    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, ndim, **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, gates=1, ndim=ndim, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h, h2h = self._conv_pre(F, x, states, i2h_weight, h2h_weight,
                                  i2h_bias, h2h_bias)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class _ConvLSTMCell(_BaseConvCell):
    _n_states = 2

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, ndim, **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, gates=4, ndim=ndim, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h, h2h = self._conv_pre(F, x, states, i2h_weight, h2h_weight,
                                  i2h_bias, h2h_bias)
        gates = i2h + h2h
        i, f, g, o = F.split(gates, num_outputs=4, axis=1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.Activation(g, act_type=self._activation)
        c = f * states[1] + i * g
        out = o * F.Activation(c, act_type=self._activation)
        return out, [out, c]


class _ConvGRUCell(_BaseConvCell):
    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, ndim, **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, gates=3, ndim=ndim, **kwargs)

    def hybrid_forward(self, F, x, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h, h2h = self._conv_pre(F, x, states, i2h_weight, h2h_weight,
                                  i2h_bias, h2h_bias)
        xr, xz, xn = F.split(i2h, num_outputs=3, axis=1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.Activation(xn + r * hn, act_type=self._activation)
        out = (1 - z) * n + z * states[0]
        return out, [out]


def _make(base, ndim, name, doc_line):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, **kwargs):
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, ndim=ndim, **kwargs)
    cls = type(name, (base,), {"__init__": __init__,
                               "__doc__": doc_line})
    return cls


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell",
                      "1D conv Elman cell (reference: "
                      "conv_rnn_cell.py Conv1DRNNCell).")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell",
                      "2D conv Elman cell.")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell",
                      "3D conv Elman cell.")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell",
                       "1D ConvLSTM (Shi et al. 2015; reference: "
                       "conv_rnn_cell.py Conv1DLSTMCell).")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell",
                       "2D ConvLSTM (Shi et al. 2015).")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell",
                       "3D ConvLSTM.")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell",
                      "1D conv GRU cell.")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell",
                      "2D conv GRU cell.")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell",
                      "3D conv GRU cell.")
