"""gluon.contrib.rnn — contributed recurrent cells.

Reference: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py,
rnn_cell.py (VariationalDropoutCell, LSTMPCell).
"""
from .conv_rnn_cell import (  # noqa: F401
    Conv1DRNNCell, Conv2DRNNCell, Conv3DRNNCell,
    Conv1DLSTMCell, Conv2DLSTMCell, Conv3DLSTMCell,
    Conv1DGRUCell, Conv2DGRUCell, Conv3DGRUCell)
from .rnn_cell import VariationalDropoutCell, LSTMPCell  # noqa: F401
