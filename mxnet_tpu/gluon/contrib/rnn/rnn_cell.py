"""Contributed recurrent cells.

Reference: python/mxnet/gluon/contrib/rnn/rnn_cell.py
(VariationalDropoutCell:33, LSTMPCell:184).
"""
from __future__ import annotations

from ...rnn.rnn_cell import ModifierCell, HybridRecurrentCell

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Variational (per-sequence) dropout around a base cell
    (reference: rnn_cell.py:33, Gal & Ghahramani 2016). One dropout
    mask per unroll is sampled for inputs/states/outputs and reused at
    every time step; ``reset()`` clears the masks."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    @staticmethod
    def _mask(like, rate):
        from .... import nd
        keep = 1.0 - rate
        return nd.random.uniform(0, 1, shape=like.shape,
                                 dtype="float32") < keep

    def _apply(self, x, rate, cache_attr):
        from .... import nd, autograd
        if rate == 0.0 or not autograd.is_training():
            return x
        mask = getattr(self, cache_attr)
        if mask is None or mask.shape != x.shape:
            mask = self._mask(x, rate).astype(x.dtype) / (1.0 - rate)
            setattr(self, cache_attr, mask)
        return x * mask

    def hybrid_forward(self, F, x, states):
        x = self._apply(x, self.drop_inputs, "_input_mask")
        if self.drop_states:
            states = [self._apply(s, self.drop_states, "_state_mask")
                      for s in states[:1]] + list(states[1:])
        out, nstates = self.base_cell(x, states)
        out = self._apply(out, self.drop_outputs, "_output_mask")
        return out, nstates

    def __repr__(self):
        return (f"VariationalDropoutCell(in={self.drop_inputs}, "
                f"state={self.drop_states}, out={self.drop_outputs}, "
                f"base={self.base_cell!r})")


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a projected hidden state (reference: rnn_cell.py:184,
    Sak et al. 2014): ``r' = P (o * tanh(c))`` with P
    (projection_size, hidden_size); the recurrent path uses the
    projected state."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def hybrid_forward(self, F, x, states, i2h_weight=None,
                       h2h_weight=None, h2r_weight=None, i2h_bias=None,
                       h2h_bias=None):
        h = self._hidden_size
        gates = (F.FullyConnected(x, i2h_weight, i2h_bias,
                                  num_hidden=4 * h)
                 + F.FullyConnected(states[0], h2h_weight, h2h_bias,
                                    num_hidden=4 * h))
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c = f * states[1] + i * g
        hidden = o * F.tanh(c)
        r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                             num_hidden=self._projection_size)
        return r, [r, c]
