"""gluon.contrib.nn — contributed layers.

Reference: python/mxnet/gluon/contrib/nn/basic_layers.py.
"""
from .basic_layers import (  # noqa: F401
    Concurrent, HybridConcurrent, Identity, SparseEmbedding,
    SyncBatchNorm, PixelShuffle1D, PixelShuffle2D, PixelShuffle3D)
