"""Contributed gluon layers.

Reference: python/mxnet/gluon/contrib/nn/basic_layers.py
(Concurrent:31, HybridConcurrent:64, Identity:97, SparseEmbedding:118,
SyncBatchNorm:165, PixelShuffle1D/2D/3D:245+).
"""
from __future__ import annotations

from ...nn.basic_layers import (Sequential, HybridSequential, BatchNorm,
                                HybridBlock, Block)
from ... import nn as _nn

__all__ = ["Concurrent", "HybridConcurrent", "Identity",
           "SparseEmbedding", "SyncBatchNorm", "PixelShuffle1D",
           "PixelShuffle2D", "PixelShuffle3D"]


class Concurrent(Sequential):
    """Run children on the same input, concat outputs (reference:
    basic_layers.py:31)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import nd
        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (reference: basic_layers.py:64).

    Overrides both ``forward`` (HybridSequential's eager path chains
    children sequentially) and ``hybrid_forward`` (the traced path)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        if self._active:
            return HybridBlock.forward(self, x, *args)
        return self.hybrid_forward(None, x)

    def hybrid_forward(self, F, x, *args, **kwargs):
        from .... import nd
        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block for Concurrent branches (reference:
    basic_layers.py:97)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose weight gradient is row-sparse (reference:
    basic_layers.py:118). Same storage-dense/gradient-sparse design as
    nn.Embedding(sparse_grad=True) — this class keeps the reference's
    contrib name."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._inner = _nn.Embedding(input_dim, output_dim, dtype=dtype,
                                    weight_initializer=weight_initializer,
                                    sparse_grad=True, params=self.params)
        self.register_child(self._inner)
        self.weight = self._inner.weight

    def forward(self, x):
        return self._inner(x)

    def __repr__(self):
        return "Sparse" + repr(self._inner)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: basic_layers.py:165 over
    src/operator/contrib/sync_batch_norm.cc). Inside a pmap/shard_map
    context pass ``axis_name``: batch moments are lax.pmean'd over that
    mesh axis (ops/nn.py _contrib_SyncBatchNorm). Outside a collective
    context it behaves exactly like BatchNorm — which on this framework
    is already correct for the single-process ShardedTrainer, since its
    batch axis is one global sharded array and XLA computes global
    moments."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", axis_name=None,
                 **kwargs):
        super().__init__(
            axis=1, momentum=momentum, epsilon=epsilon, center=center,
            scale=scale, use_global_stats=use_global_stats,
            beta_initializer=beta_initializer,
            gamma_initializer=gamma_initializer,
            running_mean_initializer=running_mean_initializer,
            running_variance_initializer=running_variance_initializer,
            in_channels=in_channels, **kwargs)
        self._axis_name = axis_name

    def hybrid_forward(self, F, x, gamma=None, beta=None,
                       running_mean=None, running_var=None):
        from .... import autograd
        training = autograd.is_training()
        kwargs = dict(self._kwargs)
        kwargs["axis_name"] = self._axis_name
        if training and not self._use_global_stats:
            out, mean, var = F._contrib_SyncBatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **kwargs)
            with autograd.pause():
                m = self._momentum
                self.running_mean.set_data(running_mean * m
                                           + mean * (1 - m))
                self.running_var.set_data(running_var * m
                                          + var * (1 - m))
            return out
        return F._contrib_SyncBatchNorm(x, gamma, beta, running_mean,
                                        running_var, **kwargs)


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        self._factors = ((factor,) * ndim
                         if isinstance(factor, int) else tuple(factor))
        assert len(self._factors) == ndim

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"


class PixelShuffle1D(_PixelShuffle):
    """(N, C*f, W) -> (N, C, W*f) (reference: basic_layers.py:245)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def hybrid_forward(self, F, x):
        from ....ops.invoke import apply_fn
        f, = self._factors

        def fwd(x):
            n, cf, w = x.shape
            c = cf // f
            return x.reshape(n, c, f, w).transpose(0, 1, 3, 2)\
                .reshape(n, c, w * f)

        return apply_fn(fwd, [x])


class PixelShuffle2D(_PixelShuffle):
    """(N, C*fh*fw, H, W) -> (N, C, H*fh, W*fw) (reference:
    basic_layers.py:293)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def hybrid_forward(self, F, x):
        from ....ops.invoke import apply_fn
        fh, fw = self._factors

        def fwd(x):
            n, c2, h, w = x.shape
            c = c2 // (fh * fw)
            return x.reshape(n, c, fh, fw, h, w)\
                .transpose(0, 1, 4, 2, 5, 3)\
                .reshape(n, c, h * fh, w * fw)

        return apply_fn(fwd, [x])


class PixelShuffle3D(_PixelShuffle):
    """(N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3) (reference:
    basic_layers.py:355)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def hybrid_forward(self, F, x):
        from ....ops.invoke import apply_fn
        f1, f2, f3 = self._factors

        def fwd(x):
            n, cf, d, h, w = x.shape
            c = cf // (f1 * f2 * f3)
            return x.reshape(n, c, f1, f2, f3, d, h, w)\
                .transpose(0, 1, 5, 2, 6, 3, 7, 4)\
                .reshape(n, c, d * f1, h * f2, w * f3)

        return apply_fn(fwd, [x])
