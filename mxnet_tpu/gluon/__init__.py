"""Gluon: imperative/hybrid neural network API
(reference: python/mxnet/gluon/)."""
from .parameter import (Parameter, Constant, ParameterDict,
                        DeferredInitializationError)  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock, CachedOp  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import loss  # noqa: F401
from . import utils  # noqa: F401


def __getattr__(name):
    # lazy: contrib imports estimator -> Trainer -> would cycle at module
    # import time
    if name == "contrib":
        import importlib
        mod = importlib.import_module(".contrib", __name__)
        globals()["contrib"] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute "
                         f"{name!r}")


def __getattr__(name):
    # heavy/cyclic subpackages load lazily
    if name in ("rnn", "data", "model_zoo", "contrib"):
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")
