"""Gluon Block / HybridBlock and the jit-backed CachedOp.

TPU-native re-design of the reference Gluon core (reference:
python/mxnet/gluon/block.py:244 ``Block``, :847 ``HybridBlock``,
src/imperative/cached_op.cc ``CachedOp``). The reference hybridizes by
re-tracing eager calls into an nnvm graph and executing it through the
CachedOp machinery (dynamic/static alloc paths). Here hybridization is
``jax.jit``: the block's eager forward — which is trace-transparent because
NDArray wraps tracers — is traced once per input signature into ONE XLA
program. XLA then does everything CachedOp's static_alloc/static_shape and
the executor's memory planner did (fusion, memory planning, scheduling),
but better, because it sees the whole program.

Mutable aux states (BatchNorm running stats) are captured during tracing as
extra jit outputs and written back after each call — the functional
equivalent of the reference's engine-mutated aux arrays.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import numpy as _np
import jax

from .. import autograd, _rng
from .. import profiler as _profiler
from ..context import Context, current_context
from ..ndarray import NDArray
from .parameter import (Parameter, ParameterDict,
                        DeferredInitializationError, _TRACE_STACK)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]


class _BlockScope:
    """Name-manager scope for automatic ``prefix`` generation
    (reference: python/mxnet/gluon/block.py:45)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_counter(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


_GLOBAL_NAME_COUNTER = {}


def _name_counter(hint):
    count = _GLOBAL_NAME_COUNTER.get(hint, 0)
    _GLOBAL_NAME_COUNTER[hint] = count + 1
    return f"{hint}{count}"


def _flatten_arrays(args):
    """Flatten nested (list/tuple of) arrays → flat list + hashable fmt.
    fmt leaf codes: -1 array (traced jit input), -2 opaque (static —
    baked into the trace and part of the jit-cache key)."""
    flat, fmt = [], []
    for a in args:
        if isinstance(a, (NDArray, jax.Array, _np.ndarray)):
            flat.append(a)
            fmt.append(-1)
        elif isinstance(a, (list, tuple)):
            sub, subfmt = _flatten_arrays(a)
            flat.extend(sub)
            fmt.append((type(a).__name__, subfmt))
        else:
            flat.append(a)
            fmt.append(-2)  # opaque non-array (scalars, None, strings)
    return flat, tuple(fmt)


def _flat_flags(fmt):
    """Per-flat-entry array flags in fmt traversal order."""
    flags = []
    for f in fmt:
        if f == -1:
            flags.append(True)
        elif f == -2:
            flags.append(False)
        else:
            flags.extend(_flat_flags(f[1]))
    return flags


def _regroup(flat, fmt):
    return _regroup_impl(flat, fmt)[0]


def _fmt_len(fmt):
    n = 0
    for f in fmt:
        n += 1 if f in (-1, -2) else _fmt_len(f[1])
    return n


def _regroup_impl(flat, fmt):
    out = []
    i = 0
    for f in fmt:
        if f in (-1, -2):
            out.append(flat[i])
            i += 1
        else:
            typ, subfmt = f
            n = _fmt_len(subfmt)
            sub, _ = _regroup_impl(flat[i:i + n], subfmt)
            out.append(tuple(sub) if typ == "tuple" else sub)
            i += n
    return out, i


class Block:
    """Base building block (reference: python/mxnet/gluon/block.py:244).

    Child blocks registered via attribute assignment; parameters live in
    ``self.params`` and are aggregated by ``collect_params``.
    """

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {_indent(repr(block), 2)}"
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)):
                raise TypeError(
                    f"Changing attribute type for {self.name} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute is not allowed."
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def __getattr__(self, name):
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    # ------------------------------------------------------------- names --
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """Aggregate parameters of self + all descendants
        (reference: block.py:546)."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for cld in self._children.values():
            ret.update(cld.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, hook)
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks, hook)
        return handle

    def apply(self, fn):
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    # -------------------------------------------------------------- init --
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as _init_mod
        if init is None:
            init = _init_mod.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def zero_grad(self):
        self.collect_params().zero_grad()

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    # ------------------------------------------------------------- state --
    def save_parameters(self, filename, deduplicate=False):
        """Save parameters to file (reference: block.py:433). Format is the
        NDArray binary map — loadable by ``load_parameters``. The write
        is crash-safe: ``nd_save`` publishes via temp-file + fsync +
        rename (resilience.atomic), so a SIGKILL mid-save leaves any
        previous ``filename`` intact, never a torn file. Returns the
        nd_save metadata (file/per-array CRC32s) for checkpoint
        manifests."""
        params = self._collect_params_with_prefix()
        from ..ndarray import save as nd_save
        arg_dict = {key: val._get_primary() for key, val in params.items()}
        return nd_save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..ndarray import load as nd_load
        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in k for k in loaded.keys()):
            # legacy ParameterDict-format file (full-prefix names)
            del loaded
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix,
                cast_dtype=cast_dtype, dtype_source=dtype_source)
            return
        if not allow_missing:
            for name in params.keys():
                assert name in loaded, \
                    f"Parameter '{name}' is missing in file '{filename}', " \
                    f"which contains parameters: {_brief_print_list(loaded.keys())}"
        for name in loaded:
            if not ignore_extra and name not in params:
                raise ValueError(
                    f"Parameter '{name}' loaded from file '{filename}' is "
                    "not present in this block")
            if name in params:
                param = params[name]
                v = loaded[name]
                if cast_dtype:
                    v = v.astype(param.dtype if dtype_source == "current"
                                 else v.dtype)
                if param._data is None:
                    param.shape = v.shape
                    if ctx is not None and isinstance(ctx, Context):
                        ctx = [ctx]
                    if not param._deferred_init:
                        param._deferred_init = (None,
                                                ctx or [current_context()],
                                                None, None)
                    init, pctx, dinit, _ = param._deferred_init
                    param._deferred_init = (init, ctx or pctx, dinit,
                                            v.asnumpy())
                    param._finish_deferred_init()
                else:
                    param.set_data(v)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # ------------------------------------------------------------ compute --
    def __call__(self, *args, **kwargs):
        # hooks see every input: keyword inputs (e.g. mask=, valid_length=)
        # are appended as a dict when present
        hook_args = args + (kwargs,) if kwargs else args
        for hook in self._forward_pre_hooks.values():
            hook(self, hook_args)
        if _profiler.scopes_enabled():
            # structure the profile: each block forward becomes a named
            # scope in the trace and in jitted HLO op metadata
            import jax
            with jax.named_scope(self.name or self.__class__.__name__):
                out = self.forward(*args, **kwargs)
        else:
            out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks.values():
            hook(self, hook_args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """No-op on plain Blocks except recursing into children
        (reference: block.py:795)."""
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a per-layer summary table (reference: block.py:615)."""
        summary = OrderedDict()
        hooks = []

        def _get_shape_str(args):
            def flatten(args):
                if not isinstance(args, (list, tuple)):
                    return [args], int(0)
                flat = []
                fmts = []
                for i in args:
                    arg, fmt = flatten(i)
                    flat.extend(arg)
                    fmts.append(fmt)
                return flat, fmts
            flat_args, _ = flatten(args)
            shapes = [x.shape for x in flat_args if isinstance(x, NDArray)]
            return str(shapes[0] if len(shapes) == 1 else shapes)

        def _register_summary_hook(block):
            def _summary_hook(block, _, outputs):
                class_name = block.__class__.__name__
                block_idx = len(summary) - 1
                m_key = f"{class_name}-{block_idx + 1}"
                summary[m_key] = OrderedDict()
                summary[m_key]["output_shape"] = _get_shape_str(outputs)
                params = 0
                summary[m_key]["trainable"] = 0
                summary[m_key]["shared"] = 0
                for p in block.params.values():
                    if p._data is None:
                        continue
                    params += p.data().size
                    summary[m_key]["trainable"] += (
                        0 if p.grad_req == "null" else p.data().size)
                summary[m_key]["n_params"] = params
            hooks.append(block.register_forward_hook(_summary_hook))

        summary["Input"] = OrderedDict()
        summary["Input"]["output_shape"] = _get_shape_str(inputs)
        summary["Input"]["n_params"] = 0
        summary["Input"]["trainable"] = 0
        summary["Input"]["shared"] = 0
        try:
            self.apply(_register_summary_hook)
            self(*inputs)
            line_format = "{:>20}  {:>42} {:>15}"
            print("-" * 80)
            print(line_format.format("Layer (type)", "Output Shape", "Param #"))
            print("=" * 80)
            total_params = 0
            trainable_params = 0
            for layer in summary:
                print(line_format.format(
                    layer, str(summary[layer]["output_shape"]),
                    summary[layer]["n_params"]))
                total_params += summary[layer]["n_params"]
                trainable_params += summary[layer]["trainable"]
            print("=" * 80)
            print(f"Total params: {total_params}")
            print(f"Trainable params: {trainable_params}")
            print("-" * 80)
        finally:
            for h in hooks:
                h.detach()


class _HookHandle:
    _next_id = 0

    def __init__(self, hooks_dict, hook):
        self._hooks_dict = hooks_dict
        self._id = _HookHandle._next_id
        _HookHandle._next_id += 1
        hooks_dict[self._id] = hook

    def detach(self):
        self._hooks_dict.pop(self._id, None)


def _indent(s, num_spaces):
    lines = s.split("\n")
    first = lines.pop(0)
    return first + "".join("\n" + " " * num_spaces + line for line in lines)


def _brief_print_list(lst, limit=7):
    lst = list(lst)
    if len(lst) > limit:
        return _brief_print_list(lst[:limit // 2], limit) + ", ..., " + \
            _brief_print_list(lst[-limit // 2:], limit)
    return ", ".join(f"'{s}'" for s in lst)


class CachedOp:
    """jit-compiled callable over a block's forward.

    TPU-native analogue of the reference CachedOp
    (src/imperative/cached_op.cc:765 Forward / :697 DynamicForward / :615
    StaticForward): one XLA program per (train-flag, input-signature).
    ``static_alloc``/``static_shape`` are accepted for parity; XLA's static
    memory planning makes them always-on.
    """

    def __init__(self, block, static_alloc=False, static_shape=False):
        self._block = block
        # keyed by (training, in_fmt, opaque_args): jit retraces when the
        # static structure changes, like the reference CachedOp re-binding
        # on signature change
        self._jits = {}
        self._meta = {}
        # snapshot once (reference CachedOp captures params at build time,
        # src/imperative/cached_op.cc); hybridize()/cast() rebuild me
        self._params_snapshot = None
        # serializes the first-call trace per signature so concurrent
        # callers never observe a half-populated _meta or another
        # thread's parameter trace state (reference ships a dedicated
        # CachedOpThreadSafe for this, src/imperative/cached_op_threadsafe.h:82;
        # here the compiled path is lock-free and only tracing locks)
        self._trace_lock = threading.Lock()

    def _trace_params(self):
        if self._params_snapshot is None:
            self._params_snapshot = [
                p for _, p in sorted(self._block.collect_params().items())]
        return self._params_snapshot

    def _make_pure(self, training, in_fmt, flags, opaque, cache_key):
        def pure(key, pvals, xvals):
            params = self._trace_params()
            block = self._block
            aux_writes = {}
            _TRACE_STACK.append(aux_writes)
            old_rng = _rng.push_trace_key(key)
            try:
                for p, v in zip(params, pvals):
                    p._trace_data = NDArray(v)
                merged, ai, oi = [], 0, 0
                for is_arr in flags:
                    if is_arr:
                        merged.append(NDArray(xvals[ai]))
                        ai += 1
                    else:
                        merged.append(opaque[oi])
                        oi += 1
                with autograd.pause(train_mode=training):
                    with _suspend_hybridization(block):
                        out = block.forward(*_regroup(merged, in_fmt))
            finally:
                for p in params:
                    p._trace_data = None
                _TRACE_STACK.pop()
                _rng.pop_trace_key(old_rng)
            flat_out, out_fmt = _flatten_arrays(
                out if isinstance(out, (list, tuple)) else [out])
            primal = [o._data if isinstance(o, NDArray) else o
                      for o in flat_out]
            aux_params = [p for p in params if p in aux_writes]
            aux_vals = [aux_writes[p]._data for p in aux_params]
            self._meta[cache_key] = (len(primal), out_fmt,
                                     not isinstance(out, (list, tuple)),
                                     aux_params)
            return tuple(primal) + tuple(aux_vals)
        return pure

    def _run(self, jitfn, recording, key, pvals, xvals):
        fn = lambda key, *a: jitfn(  # noqa: E731
            key, a[:len(pvals)], a[len(pvals):])
        if recording:
            outs, vjp_fn = jax.vjp(fn, key, *pvals, *xvals)
        else:
            outs, vjp_fn = fn(key, *pvals, *xvals), None
        return fn, outs, vjp_fn

    def __call__(self, *args):
        from ..ops.invoke import as_jax
        flat_in, in_fmt = _flatten_arrays(args)
        flags = _flat_flags(in_fmt)
        arrays = [v for v, f in zip(flat_in, flags) if f]
        opaque = tuple(v for v, f in zip(flat_in, flags) if not f)
        training = autograd.is_training()
        cache_key = (training, in_fmt, opaque)
        try:
            hash(cache_key)
        except TypeError:
            raise TypeError(
                "hybridized blocks require non-array arguments to be "
                f"hashable (got {opaque!r}); pass arrays or hashable "
                "constants, or skip hybridize() for this block") from None
        params = self._trace_params()
        recording = autograd.is_recording()
        key = _rng.next_key()

        def _prologue():
            # resolve deferred shapes/init, then snapshot leaf values
            if any(p._data is None and (p.shape is None or 0 in p.shape)
                   for p in params):
                # deferred shapes unresolved: one eager warm-up pass
                # infers them (≙ the reference's deferred-compute trace
                # in _build_cache, block.py:978); predict mode so BN aux
                # states are untouched
                with _suspend_hybridization(self._block):
                    with autograd.pause(train_mode=False):
                        self._block(*args)
            for p in params:
                p._finish_deferred_init()
            pvals = tuple(p.data()._data for p in params)
            xvals = tuple(as_jax(x) for x in arrays)
            return pvals, xvals

        uninitialized = any(p._data is None for p in params)
        jitfn = self._jits.get(cache_key)
        if uninitialized or jitfn is None or cache_key not in self._meta:
            # slow path: first call for this signature (or params still
            # deferred). Serialize init + trace so concurrent callers
            # never observe half-initialized params or a half-populated
            # _meta; once traced, the compiled path below is lock-free.
            with self._trace_lock:
                pvals, xvals = _prologue()
                jitfn = self._jits.get(cache_key)
                if jitfn is None:
                    jitfn = jax.jit(self._make_pure(training, in_fmt, flags,
                                                    opaque, cache_key))
                    self._jits[cache_key] = jitfn
                fn, outs, vjp_fn = self._run(jitfn, recording, key,
                                             pvals, xvals)
        else:
            pvals, xvals = _prologue()
            fn, outs, vjp_fn = self._run(jitfn, recording, key, pvals, xvals)

        n_primal, out_fmt, single, aux_params = self._meta[cache_key]
        primal, aux = outs[:n_primal], outs[n_primal:]
        results = [NDArray(o) for o in primal]

        if recording:
            in_slots = [None]
            in_slots += [getattr(p.data(), "_ag_slot", None) for p in params]
            in_slots += [getattr(x, "_ag_slot", None) for x in arrays]
            out_slots = [autograd.new_slot() for _ in outs]
            out_avals = [(tuple(o.shape), o.dtype) for o in outs]
            for r, s in zip(results, out_slots):
                r._ag_slot = s

            def _vjp(cots, _f=vjp_fn):
                # pure() always returns a tuple; the tape passes a bare
                # cotangent when there is exactly one output slot
                if not isinstance(cots, tuple):
                    cots = (cots,)
                return _f(cots)

            def _fn_taped(*a, _fn=fn):
                # output structure must match the tape's cotangent
                # convention (bare when single) so create_graph=True can
                # re-derive this vjp differentiably
                outs_ = _fn(*a)
                return outs_[0] if len(outs_) == 1 else outs_
            autograd.record_node(_vjp, in_slots, out_slots, out_avals,
                                 fn=_fn_taped,
                                 xs=(key,) + tuple(pvals) + tuple(xvals))

        # write captured aux states (running means etc.) back
        for p, v in zip(aux_params, aux):
            p._trace_data = None
            p.set_data(NDArray(v))

        grouped = _regroup(results, out_fmt)
        return grouped[0] if single else grouped


class _SuspendTLS(threading.local):
    def __init__(self):
        self.blocks = set()


_suspend_tls = _SuspendTLS()


class _suspend_hybridization:
    """Run block.forward through the eager path instead of recursively
    calling the CachedOp. The suspension is THREAD-LOCAL (a per-thread
    set of suspended block ids, not a flip of the shared ``_active``
    flag): while one thread traces, other threads serving the same net
    must keep hitting the compiled path — flipping ``_active`` would
    route them into the eager path mid-trace (thread-safe serving,
    reference: src/imperative/cached_op_threadsafe.h:82)."""

    def __init__(self, block):
        self._block = block
        self._added = []

    def __enter__(self):
        suspended = _suspend_tls.blocks

        def _save(b):
            if isinstance(b, HybridBlock) and id(b) not in suspended:
                suspended.add(id(b))
                self._added.append(id(b))
        self._block.apply(_save)

    def __exit__(self, *exc):
        _suspend_tls.blocks.difference_update(self._added)


class HybridBlock(Block):
    """A Block that can be traced into one XLA program
    (reference: python/mxnet/gluon/block.py:847).

    Subclasses implement ``hybrid_forward(F, x, *, <param kwargs>)``; ``F``
    is the ``nd`` namespace (there is no separate symbolic namespace — the
    eager API is trace-transparent, so one code path serves both modes).
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._cached_op_lock = threading.Lock()
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def _get_cached_op(self):
        # double-checked: without the lock two threads' first calls
        # would build two CachedOps with independent _trace_locks,
        # un-serializing the first-trace warm-up they exist to guard
        if self._cached_op is None:
            with self._cached_op_lock:
                if self._cached_op is None:
                    self._cached_op = CachedOp(self, **{
                        k: v for k, v in self._flags.items()
                        if k in ("static_alloc", "static_shape")})
        return self._cached_op

    def infer_shape(self, *args):
        """Infer deferred parameter shapes from inputs. Layers override
        ``_infer_param_shapes`` (reference uses graph shape inference)."""
        self._infer_param_shapes(*args)

    def _infer_param_shapes(self, *args):
        pass

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def serve(self, example_input=None, **server_kwargs):
        """Serve this block's forward directly (no export step) through
        a :class:`mxnet_tpu.serving.ModelServer`: dynamic micro-batching
        of concurrent requests, bucket padding, warmup pre-compiles.

        ``example_input`` (a single sample, NO batch dim) resolves any
        deferred parameter shapes and pins the server's item
        shape/dtype so ``warmup()`` works before the first request.
        Returns an **unstarted** server — call ``start()`` (or use it
        as a context manager)::

            with net.serve(example_input=x0, max_batch_size=16) as srv:
                srv.warmup()
                fut = srv.submit(x0)
        """
        from ..serving import ModelServer
        if example_input is not None:
            ex = _np.asarray(example_input._data
                             if isinstance(example_input, NDArray)
                             else example_input)
            with autograd.pause(train_mode=False):
                self(NDArray(ex[None]))       # resolve deferred shapes
            server_kwargs.setdefault("item_shape", ex.shape)
            server_kwargs.setdefault("dtype", ex.dtype)
        return ModelServer(self, **server_kwargs)

    def __call__(self, *args, **kwargs):
        return super().__call__(*args, **kwargs)

    def forward(self, x, *args):
        if self._active and not _TRACE_STACK and \
                id(self) not in _suspend_tls.blocks:
            # cached op resolves deferred init itself; don't touch params
            # on the hot path
            return self._get_cached_op()(x, *args)
        try:
            params = {k: v.data() for k, v in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
            for _, p in self._reg_params.items():
                p._finish_deferred_init()
            params = {k: v.data() for k, v in self._reg_params.items()}
        from .. import ndarray as F
        return self.hybrid_forward(F, x, *args, **params)

    def _deferred_infer_shape(self, *args):
        try:
            self.infer_shape(*args)
        except Exception as e:
            raise ValueError(
                f"Deferred initialization failed because shape cannot be "
                f"inferred: {e}") from e

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Export model params for deployment (reference: block.py:1241).
        Graph JSON export requires the Symbol API (see mxnet_tpu.symbol)."""
        params = self._collect_params_with_prefix()
        from ..ndarray import save as nd_save
        arg_dict = {f"arg:{k}": v._get_primary() for k, v in params.items()
                    if v.grad_req != "null"}
        arg_dict.update({f"aux:{k}": v._get_primary()
                         for k, v in params.items() if v.grad_req == "null"})
        pfile = f"{path}-{epoch:04d}.params"
        nd_save(pfile, arg_dict)
        return pfile

    def optimize_for(self, x, *args, backend=None, **kwargs):
        """Partial parity: backend partitioning is XLA's job here."""
        self.hybridize(True)
        return self(x, *args)


class SymbolBlock(HybridBlock):
    """Construct a block from a Symbol graph (reference: block.py:1403)."""

    def __init__(self, outputs, inputs, params=None):
        # empty prefix: symbol argument names ARE the parameter names
        # (a generated prefix would break forward()'s eval bindings)
        super().__init__(prefix="", params=params)
        from ..symbol import Symbol
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if not isinstance(outputs, Symbol):
            raise TypeError("outputs must be a Symbol")
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) \
            else [inputs]
        input_names = {i.name for i in self._inputs}
        for name in outputs.list_arguments():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            self.params.get(name, allow_deferred_init=True,
                            grad_req="null")

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load
        from ..symbol import var as sym_var
        sym = sym_load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_var(n) for n in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            ret.load_parameters(param_file, ctx=ctx, cast_dtype=True,
                                dtype_source="saved")
        return ret

    def forward(self, x, *args):
        input_names = [i.name for i in self._inputs]
        arg_arrays = {}
        for name, p in self.collect_params().items():
            try:
                arg_arrays[name] = p.data()
            except DeferredInitializationError:
                # infer parameter shapes from the input shapes via the
                # symbol's shape solver, then materialize
                known = {n: v.shape for n, v in
                         zip(input_names, (x,) + args)}
                shape_of, _, _ = self._outputs._solve_shapes(known,
                                                          partial=True)
                for pname, pp in self.collect_params().items():
                    if pname in shape_of and pp._data is None:
                        pp.shape = shape_of[pname]
                        pp._finish_deferred_init()
                try:
                    arg_arrays = {n: pp.data() for n, pp in
                                  self.collect_params().items()}
                except DeferredInitializationError:
                    raise RuntimeError(
                        f"Parameter {name} of SymbolBlock could not be "
                        "shape-inferred from the inputs — load params or "
                        "initialize() with explicit shapes first"
                    ) from None
                break
        bindings = dict(zip(input_names, (x,) + args))
        bindings.update(arg_arrays)
        return self._outputs.eval_dict(bindings)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
