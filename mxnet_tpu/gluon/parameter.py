"""Gluon Parameter / ParameterDict.

TPU-native re-design of the reference's parameter system (reference:
python/mxnet/gluon/parameter.py — ``Parameter`` with deferred init,
per-context replicas, grad_req plumbing; ``ParameterDict`` with prefix
namespacing). Differences from the reference, by design:

- Storage is one ``NDArray`` per ``Context``; on TPU the idiomatic
  multi-device story is *sharding one array over a Mesh* (see
  ``mxnet_tpu.parallel``), so per-ctx replication exists only for API
  parity with reference data-parallel code.
- ``attach_grad`` on the underlying array wires the vjp-tape autograd; the
  reference instead allocates grad buffers bound into executors.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

import numpy as _np

from .. import initializer
from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from ..ndarray import NDArray

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's data is requested before shape inference
    completed (reference: gluon/parameter.py:38)."""


# Trace-capture stack used by CachedOp (gluon.block): while a hybridized
# block is traced into jit, parameter reads must return tracer-backed
# arrays and aux-state writes (BatchNorm running stats) must be captured as
# extra jit outputs instead of touching concrete buffers.
#
# Thread-local: concurrent inference from N Python threads (the reference
# ships CachedOpThreadSafe for this, src/imperative/cached_op_threadsafe.h:82)
# must not see another thread's in-progress trace.
class _ThreadLocalStack(threading.local):
    def __init__(self):
        self._stack = []

    def append(self, item):
        self._stack.append(item)

    def pop(self):
        return self._stack.pop()

    def __bool__(self):
        return bool(self._stack)

    def __len__(self):
        return len(self._stack)

    def __getitem__(self, idx):
        return self._stack[idx]


_TRACE_STACK = _ThreadLocalStack()


class Parameter:
    """A Block parameter: named, lazily-shaped, context-replicated tensor.

    Reference: python/mxnet/gluon/parameter.py:51 ``class Parameter``.
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = None
        if shape is not None and not isinstance(shape, (tuple, list)):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self.stype = stype
        self.grad_stype = grad_stype
        # ctx -> NDArray (must exist before the grad_req setter runs)
        self._data: Optional[OrderedDict] = None
        self.grad_req = grad_req
        self._deferred_init = ()
        # per-thread tracer-backed NDArray during CachedOp trace: thread A
        # tracing must not leak tracers into thread B's concurrent forward
        self._trace_tls = threading.local()
        # serializes deferred init: two threads' first forwards must not
        # both draw+write this parameter (pickling is via __reduce__, so
        # the lock never reaches a pickle stream)
        self._init_lock = threading.Lock()
        self.attributes = {}
        self._var = None

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={self.dtype})")

    @property
    def _trace_data(self):
        return getattr(self._trace_tls, "value", None)

    @_trace_data.setter
    def _trace_data(self, v):
        self._trace_tls.value = v

    # ------------------------------------------------------------- props --
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError(f"grad_req must be write/add/null, got {req}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if self._data is not None:
            for arr in self._data.values():
                if req == "null":
                    arr._grad = None
                    arr._grad_req = "null"
                else:
                    arr.attach_grad(req)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # merge unknown (0) dims — reference gluon/parameter.py shape setter
        assert len(self._shape) == len(new_shape) and all(
            j in (0, i) or i == 0 for i, j in zip(new_shape, self._shape)), \
            f"Expected shape {new_shape} is incompatible with given shape " \
            f"{self._shape} for Parameter {self.name}"
        self._shape = tuple(n if o == 0 else o
                            for o, n in zip(self._shape, new_shape))

    # -------------------------------------------------------------- init --
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    f"Parameter '{self.name}' has not been initialized yet "
                    "because initialization was deferred. Actual "
                    "initialization happens during the first forward pass. "
                    "Please pass one batch of data through the network "
                    "before accessing Parameters.")
            raise RuntimeError(
                f"Parameter '{self.name}' has not been initialized. Note "
                "that you should initialize parameters and create Trainer "
                "with Block.collect_params() instead of Block.params "
                "because the later does not include Parameters of nested "
                "child Blocks")
        if ctx is not None and ctx not in self._data:
            raise RuntimeError(
                f"Parameter '{self.name}' was not initialized on context "
                f"{ctx}. It was only initialized on {list(self._data)}.")

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Materialize data on ``ctx`` (reference: parameter.py:365)."""
        if default_init is None:
            default_init = initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if self._shape is None or any(s == 0 for s in self._shape):
            if self._allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                "invalid shape: {}.".format(self._shape))
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        with self._init_lock:
            if not self._deferred_init:   # another thread finished it
                return
            self._finish_deferred_init_locked()

    def _finish_deferred_init_locked(self):
        init, ctx, default_init, data = self._deferred_init
        assert self._shape is not None and all(self._shape), \
            f"Parameter {self.name} has unresolved shape {self._shape}"
        if data is None:
            buf = _np.zeros(self._shape, dtype=dtype_np(self.dtype))
            if init is not None:
                # initializers write via slice assignment; a numpy-backed
                # shim keeps one-shot init off-device (no jit churn)
                arr = _InitBuffer(buf)
                ini = initializer.create(init) if isinstance(init, str) else init
                desc = initializer.InitDesc(self.name, self.attributes)
                ini(desc, arr)
                buf = arr._buf
            data = buf
        else:
            data = data.asnumpy() if isinstance(data, NDArray) else data
        self._init_impl(data, ctx)
        # cleared only after _data exists: a racing thread that saw
        # _deferred_init truthy blocks on the lock, re-checks, returns
        self._deferred_init = ()

    def _init_impl(self, data, ctx_list):
        # build fully, then publish: concurrent readers must never see a
        # partially-filled ctx map
        filled = OrderedDict()
        for c in ctx_list:
            arr = NDArray(_np.asarray(data, dtype=dtype_np(self.dtype)),
                          ctx=c)
            if self._grad_req != "null":
                arr.attach_grad(self._grad_req)
            filled[c] = arr
        self._data = filled

    # -------------------------------------------------------------- data --
    def _get_primary(self):
        self._check_initialized()
        return next(iter(self._data.values()))

    def data(self, ctx=None):
        """Return data on ``ctx`` (tracer-backed during hybridize trace)."""
        if self._trace_data is not None:
            return self._trace_data
        if _TRACE_STACK:
            # a concrete read under an active trace frame bakes this
            # parameter's value into the compiled program as a constant;
            # frames that track reads (jit.CompiledTrainStep) use the
            # set to promote such parameters to program inputs /
            # guard the cache entry (CachedOp frames are plain dicts)
            reads = getattr(_TRACE_STACK[-1], "reads", None)
            if reads is not None:
                reads.add(self)
        if self._data is None and self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' not initialized yet (deferred).")
        self._check_initialized(ctx)
        if ctx is not None:
            return self._data[ctx]
        return self._get_primary()

    def list_data(self):
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None):
        d = self.data(ctx)
        if d._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                f"because grad_req='{self._grad_req}'")
        return d._grad

    def list_grad(self):
        self._check_initialized()
        return [self.grad(c) for c in self._data]

    def zero_grad(self):
        if self._data is None:
            return
        for arr in self._data.values():
            if arr._grad is not None:
                arr._grad[:] = 0

    def set_data(self, data):
        """Set value on all contexts; inside a CachedOp trace this captures
        the write as an extra jit output (aux-state semantics — reference
        aux states are engine-mutated, here threaded functionally)."""
        self.shape = data.shape
        if _TRACE_STACK and isinstance(data, NDArray):
            import jax
            if isinstance(data._data, jax.core.Tracer):
                _TRACE_STACK[-1][self] = data
                self._trace_data = data
                return
        if self._data is None:
            assert self._deferred_init, \
                f"Parameter '{self.name}' has not been initialized"
            init, ctx, default_init, _ = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
            return
        import jax
        import jax.numpy as jnp
        src = data if isinstance(data, NDArray) else NDArray(data)
        # value-copy semantics (reference set_data: dst[:]=src): when the
        # source is backed by a live jax buffer, a same-device device_put
        # shares it, and the source's owner must not observe this
        # parameter's subsequent in-place (donated) optimizer updates.
        # Host-sourced data and cross-device placements already
        # materialize fresh buffers — only same-device targets must copy.
        try:
            src_devs = src._data.devices() \
                if isinstance(data, (NDArray, jax.Array)) else frozenset()
        except Exception:
            src_devs = frozenset()  # tracer-backed source cannot alias
        for c in list(self._data):
            arr = NDArray(src._data, ctx=c, dtype=self.dtype)
            if c.jax_device in src_devs:
                arr._data = jnp.copy(arr._data)
            self._data[c] = arr
            if self._grad_req != "null":
                self._data[c].attach_grad(self._grad_req)

    def row_sparse_data(self, row_id):
        """Row-sparse view of the requested rows (reference:
        parameter.py row_sparse_data). Storage stays dense on TPU (XLA
        has no sparse buffers); the returned RowSparseNDArray holds only
        the gathered rows, so the sparse *access pattern* is preserved."""
        if self.stype != "row_sparse":
            raise RuntimeError(
                f"Parameter '{self.name}' stype is {self.stype!r}; "
                "row_sparse_data requires stype='row_sparse'")
        import jax.numpy as jnp
        from ..ndarray.sparse import RowSparseNDArray
        src = self.data()
        rows = row_id._data if isinstance(row_id, NDArray) else \
            jnp.asarray(row_id, jnp.int32)
        rows = jnp.unique(rows.astype(jnp.int32).ravel())
        return RowSparseNDArray(src._data[rows], rows, src.shape)

    def list_row_sparse_data(self, row_id):
        return [self.row_sparse_data(row_id)]

    # --------------------------------------------------------------- ctx --
    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError(
                f"Parameter '{self.name}' has not been initialized")
        return list(self._data)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = self._get_primary()
            self._init_impl(data.asnumpy(), ctx)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError(
                f"Cannot reset context for Parameter '{self.name}' because "
                "it has not been initialized.")

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        for c in list(self._data):
            arr = self._data[c].astype(dtype)
            if self._grad_req != "null":
                arr.attach_grad(self._grad_req)
            self._data[c] = arr

    def var(self):
        """Symbol variable for this parameter (legacy Symbol API)."""
        if self._var is None:
            from ..symbol import var
            self._var = var(self.name, shape=self.shape, dtype=self.dtype)
        return self._var

    def __reduce__(self):
        state = (self.name, self._grad_req, self._shape, self.dtype,
                 self.lr_mult, self.wd_mult)
        return (_rebuild_parameter, state +
                (self._get_primary().asnumpy() if self._data is not None
                 else None,))


def _rebuild_parameter(name, grad_req, shape, dtype, lr_mult, wd_mult, data):
    p = Parameter(name, grad_req=grad_req, shape=shape, dtype=dtype,
                  lr_mult=lr_mult, wd_mult=wd_mult)
    if data is not None:
        p.initialize(init=initializer.Constant(0))
        p.set_data(NDArray(data))
    return p


class _InitBuffer:
    """numpy-backed slice-assignable shim handed to initializers."""

    def __init__(self, buf):
        self._buf = buf

    @property
    def shape(self):
        return self._buf.shape

    @property
    def dtype(self):
        return self._buf.dtype

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value.asnumpy()
        self._buf[key] = value

    def __getitem__(self, key):
        return self._buf[key]

    def asnumpy(self):
        return self._buf

    def copyto(self, other):
        other[:] = self._buf
        return other


class Constant(Parameter):
    """Non-trainable constant parameter (reference: parameter.py:772)."""

    def __init__(self, name, value):
        if not isinstance(value, _np.ndarray):
            value = (value.asnumpy() if isinstance(value, NDArray)
                     else _np.asarray(value))
        self.value = value

        class ConstInit(initializer.Initializer):
            def _init_weight(self, _, arr):
                arr[:] = value

            def _init_default(self, _, arr):
                arr[:] = value

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, differentiable=False,
                         init=ConstInit())


class ParameterDict:
    """Ordered dict of Parameters with a shared prefix
    (reference: python/mxnet/gluon/parameter.py:817)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{s}\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Get-or-create ``prefix+name`` (reference: parameter.py:884)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None:
                        param.shape = v
                        continue
                    if k == "init" and (v is None or existing is None):
                        continue
                    assert v is None or v == existing, \
                        f"Cannot retrieve Parameter '{name}' because " \
                        f"desired attribute does not match with stored " \
                        f"for attribute '{k}': desired '{v}' vs " \
                        f"stored '{existing}'"
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(
                    f"No constant named '{name}'. Please specify value "
                    "if you want to create a new constant.")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    f"Cannot update self with other because they have " \
                    f"different Parameters with the same name '{k}'"
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def list_ctx(self):
        s = set()
        for v in self.values():
            s.update(v.list_ctx())
        return sorted(s, key=repr)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save
        arg = {}
        for param in self.values():
            weight = param._get_primary()
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    f"Prefix '{strip_prefix}' is to be stripped before "
                    f"saving, but Parameter's name '{param.name}' does not "
                    f"start with '{strip_prefix}'")
            arg[param.name[len(strip_prefix):]] = weight
        nd_save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix="", cast_dtype=False,
             dtype_source="current"):
        from ..ndarray import load as nd_load
        loaded = nd_load(filename)
        arg_dict = {restore_prefix + k.split(":", 1)[-1]: v
                    for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter '{name}' is missing in file '{filename}'"
        for name, v in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise ValueError(
                        f"Parameter '{name}' loaded from file "
                        f"'{filename}' is not present in this ParameterDict")
                continue
            param = self._params[name]
            if cast_dtype:
                v = v.astype(param.dtype if dtype_source == "current"
                             else v.dtype)
            if param._data is None:
                param.shape = v.shape
                if isinstance(ctx, Context):
                    ctx = [ctx]
                param._deferred_init = param._deferred_init or \
                    (None, ctx or [current_context()], None, None)
                init, pctx, dinit, _ = param._deferred_init
                param._deferred_init = (init, ctx or pctx, dinit,
                                        v.asnumpy())
                param._finish_deferred_init()
            else:
                param.set_data(v)
