"""Gluon Trainer.

Reference: python/mxnet/gluon/trainer.py:29 — applies an Optimizer to a set
of Parameters, wiring gradient aggregation through a KVStore. TPU-native
differences: on one host "allreduce over contexts" is a plain sum (no
NCCL/P2P machinery needed — XLA handles device placement), and the
multi-device path of record is sharding via mxnet_tpu.parallel; the kvstore
seam is kept so reference training loops run unmodified.
"""
from __future__ import annotations

from ..ndarray import NDArray
from .. import optimizer as opt
from .parameter import Parameter

__all__ = ["Trainer"]

_TREE_SUM = None


def _tracer():
    from ..observability.tracing import get_tracer
    return get_tracer()


def _tree_sum_jit():
    """One jitted program summing each parameter's per-context replicas
    (input: tuple over params of tuple over ctx of arrays, all staged on
    one device). jit re-traces per (structure, shapes) signature, so one
    callable serves every model."""
    global _TREE_SUM
    if _TREE_SUM is None:
        import jax

        def _tree_sum(gs_lists):
            out = []
            for gs in gs_lists:
                total = gs[0]
                for g in gs[1:]:
                    total = total + g
                out.append(total)
            return out

        _TREE_SUM = jax.jit(_tree_sum)
    return _TREE_SUM


class Trainer:
    """Optimizer driver over a ParameterDict
    (reference: gluon/trainer.py:29)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        param_list = []
        if isinstance(params, (dict,)) or hasattr(params, "items"):
            for key in sorted(list(params.keys())):
                param_list.append(params[key])
            params = param_list
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._contains_sparse_weight = False
        self._step_count = 0
        self._obs = None
        self._fused = None  # lazy optimizer.fused.FusedUpdater
        import weakref
        self._compiled_steps = weakref.WeakSet()
        self._restored_step_state = None
        self._ckpt_mgrs = {}   # realpath(run_dir) -> CheckpointManager

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """Create the kvstore lazily (reference: trainer.py:183). With a
        single context the 'device'/'local' stores reduce to direct
        updates; 'dist' maps to the collective tpu backend."""
        config = self._kvstore_params
        kv = config["kvstore"]
        if kv is None or kv in ("", "nullkv"):
            self._kvstore = None
            self._update_on_kvstore = False
        elif isinstance(kv, str):
            from .. import kvstore as kvs
            ctxs = self._params[0].list_ctx() if self._params else []
            if kv in ("local", "device") and len(ctxs) <= 1:
                # single device: kvstore adds nothing, update in place
                self._kvstore = None
                self._update_on_kvstore = False
            else:
                self._kvstore = kvs.create(kv)
                self._update_on_kvstore = (
                    config["update_on_kvstore"]
                    if config["update_on_kvstore"] is not None
                    else self._kvstore.is_capable("optimizer"))
                if self._update_on_kvstore:
                    self._kvstore.set_optimizer(self._optimizer)
        else:
            self._kvstore = kv
            self._update_on_kvstore = bool(config["update_on_kvstore"])
        if self._kvstore is not None:
            for i, param in enumerate(self._params):
                self._kvstore.init(i, param.data())
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _obs_metrics(self):
        if self._obs is None:
            import os
            from ..observability import get_registry
            reg = get_registry()
            self._obs = {
                "steps": reg.counter(
                    "mxtpu_training_optimizer_steps_total",
                    "Trainer.step calls (allreduce + update)."),
                "secs": reg.histogram(
                    "mxtpu_training_optimizer_step_seconds",
                    "Time inside Trainer.step (allreduce + update)."),
                "examples": reg.counter(
                    "mxtpu_training_examples_total",
                    "Examples processed (sum of Trainer.step "
                    "batch sizes)."),
                "grad_norm": reg.gauge(
                    "mxtpu_training_grad_norm",
                    "Global L2 gradient norm of the last step "
                    "(MXNET_TPU_METRICS_GRAD_NORM=1 only; costs a "
                    "host sync)."),
                "want_grad_norm": os.environ.get(
                    "MXNET_TPU_METRICS_GRAD_NORM") == "1",
                "upd_dispatch": reg.counter(
                    "mxtpu_trainer_update_dispatch_total",
                    "Compiled optimizer-update program launches "
                    "(fused path: 1 per step regardless of parameter "
                    "count)."),
                "upd_fused": reg.counter(
                    "mxtpu_trainer_update_fused_total",
                    "Trainer.step updates applied as one fused, "
                    "buffer-donating dispatch."),
                "upd_fallback": reg.counter(
                    "mxtpu_trainer_update_fallback_total",
                    "Trainer.step updates that ran the per-param loop, "
                    "by reason.", ("reason",)),
            }
        return self._obs

    def _observe_grad_norm(self, obs):
        """Global L2 norm over all gradients — opt-in: the asnumpy()
        fetch forces a device sync, which pipelined training loops must
        not pay by default. Only the primary grad copy is normed: after
        ``_allreduce_grads`` every device copy holds the same reduced
        value, so summing all copies would inflate the norm by
        sqrt(num_devices). (With ``update_on_kvstore`` the local copy is
        the pre-reduction gradient — the norm is then per-worker, not
        global.)"""
        import numpy as _np
        total = 0.0
        for param in self._params:
            if param.grad_req == "null" or param._data is None:
                continue
            g = param.list_grad()[0]
            a = _np.asarray(g.asnumpy(), dtype=_np.float64)
            total += float((a * a).sum())
        obs["grad_norm"].set(total ** 0.5)

    def _fused_updater(self):
        if self._fused is None:
            from ..optimizer.fused import FusedUpdater
            self._fused = FusedUpdater(self._optimizer, self._updaters[0])
        return self._fused

    def _fold_reduce_ok(self, obs, fused_reason):
        """True when the gradient reduce can be folded into the fused
        update program (allreduce + update = one dispatch). Requires the
        fused path to be eligible (``fused_reason is None``), the
        grad-norm observer off (it reads the reduced gradients in
        place), and a reduce the compiled step can express: per-context
        replicas with no kvstore, or an attached in-process store whose
        reduce is a plain sum."""
        if self._update_on_kvstore or obs["want_grad_norm"]:
            return False
        if fused_reason is not None:
            return False
        replicated = any(
            p.grad_req != "null" and p._data is not None
            and len(p._data) > 1 for p in self._params)
        if self._kvstore is None:
            return replicated
        return replicated and getattr(
            self._kvstore, "fused_reduce_compatible", False)

    def compile_step(self, loss_fn, buckets=None, donate=True, remat=None,
                     mesh=None, param_spec=None):
        """Compile the WHOLE training step — forward + loss + backward +
        cross-context gradient reduce + optimizer update — into one
        buffer-donating XLA program per input signature
        (:class:`mxnet_tpu.jit.CompiledTrainStep`).

        ``loss_fn(*batch)`` is ordinary eager Python calling the net
        (the ops are trace-transparent); it returns the per-sample loss,
        or a tuple ``(loss, *extras)`` whose extras (predictions, ...)
        ride along as program outputs. The returned step object replaces
        the ``record()/backward()/step()`` triple::

            step = trainer.compile_step(lambda x, y: loss(net(x), y))
            for x, y in loader:          # ideally a DevicePrefetchIter
                l = step(x, y)           # ONE device dispatch

        Steps that cannot compile (sparse grads, host-sync optimizers,
        data-dependent Python control flow, ``grad_req='add'``) fall
        back to the eager path per step, counted by reason on
        ``mxtpu_train_step_fallback_total``. ``remat`` ('full'/'dots')
        rematerializes the backward for memory headroom (bigger
        batches). See docs/PERFORMANCE.md.

        ``mesh`` (a ``jax.sharding.Mesh``, a ``parse_mesh`` string like
        ``"dp=4,tp=2"``, or the ``MXNET_TPU_MESH`` env default) turns
        the step into ONE SPMD program over the device mesh: batches
        shard over ``dp``, weights follow ``param_spec`` (e.g.
        ``parallel.auto_spec(net, mesh)``; default replicated), and the
        gradient reduce happens in-program — still one dispatch per
        step at any device count. The trainer must be single-context;
        per-context replicas and a mesh are two incompatible placements
        (``mesh_multictx`` fallback). See docs/PERFORMANCE.md §SPMD.
        """
        import os
        from ..jit import CompiledTrainStep
        if mesh is None:
            mesh = os.environ.get("MXNET_TPU_MESH") or None
        return CompiledTrainStep(self, loss_fn, buckets=buckets,
                                 donate=donate, remat=remat, mesh=mesh,
                                 param_spec=param_spec)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + optimizer update (reference: trainer.py:329).

        On the fused path this is ONE compiled dispatch; when the
        reduce folds in (multi-context, plain-sum store), the summed
        gradient exists only inside the program — ``param.list_grad()``
        afterwards holds the per-context partials. Readers of reduced
        gradients should set ``MXNET_TPU_FUSED_UPDATE=0`` (see
        docs/PERFORMANCE.md)."""
        import time as _time
        if not self._kv_initialized:
            self._init_kvstore()
        obs = self._obs_metrics()
        t0 = _time.monotonic()
        with _tracer().span("mxtpu.trainer.step", "step", None, None,
                            self._step_count):
            self._optimizer.rescale_grad = self._scale / batch_size
            fused_reason = self._fused_updater().why_ineligible(
                self._params, ignore_stale_grad)
            fold = self._fold_reduce_ok(obs, fused_reason)
            if not fold:
                self._allreduce_grads()
            if obs["want_grad_norm"]:
                try:
                    self._observe_grad_norm(obs)
                except Exception:
                    pass
            self._update(ignore_stale_grad, _fold_reduce=fold,
                         _fused_reason=fused_reason)
        obs["secs"].observe(_time.monotonic() - t0)
        obs["steps"].inc()
        obs["examples"].inc(batch_size)
        self._step_count += 1
        from ..resilience import faults
        from ..resilience import async_writer as _aw
        _aw.note_step_overlap()
        faults.on_step(self._step_count)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise AssertionError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported. Try setting `update_on_kvstore` to False "
                "when creating trainer.")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is not None:
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                self._kvstore.push(i, param.list_grad(), priority=-i)
                if not self._update_on_kvstore:
                    self._kvstore.pull(i, param.list_grad(), priority=-i)
            return
        # sum over contexts then broadcast (reference Comm*::Reduce,
        # src/kvstore/comm.h:122) — ONE compiled tree-level sum over every
        # parameter's replicas instead of an O(n_params * n_ctx) chain of
        # `total = total + g` adds and per-grad copy-backs
        work = [param for param in self._params
                if param.grad_req != "null" and param._data is not None
                and len(param._data) > 1]
        if not work:
            return
        import jax
        primary = work[0].list_grad()[0].context.jax_device
        staged = tuple(
            tuple(g._data if g.context.jax_device == primary
                  else jax.device_put(g._data, primary)
                  for g in param.list_grad())
            for param in work)
        totals = _tree_sum_jit()(staged)
        for param, total in zip(work, totals):
            for g in param.list_grad():
                dev = g.context.jax_device
                g._data = total if dev == primary \
                    else jax.device_put(total, dev)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        assert not self._update_on_kvstore, \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False, _fold_reduce=False,
                _fused_reason="unchecked"):
        if self._update_on_kvstore:
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                self._kvstore.pull(i, param.list_data(), priority=-i)
            return
        obs = self._obs_metrics()
        fused = self._fused_updater()
        reason = _fused_reason if _fused_reason != "unchecked" else \
            fused.why_ineligible(self._params, ignore_stale_grad)
        if reason is None:
            if fused.step(self._params, fold_reduce=_fold_reduce):
                launched = getattr(fused, "last_dispatches", 1)
                obs["upd_dispatch"].inc(launched)
                obs["upd_fused"].inc(launched)
                return
            reason = fused.last_fallback_reason or "runtime"
        if _fold_reduce:
            # the reduce was deferred into the (not-taken) fused program
            self._allreduce_grads()
        obs["upd_fallback"].labels(reason=reason).inc()
        updater = self._updaters[0]
        dispatches = 0
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._data is None:
                continue
            for w, g in zip(param.list_data(), param.list_grad()):
                updater(i, g, w)
                dispatches += 1
        obs["upd_dispatch"].inc(dispatches)

    def save_states(self, fname):
        """Save optimizer/updater states (reference: trainer.py:470)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        # dump the optimizer itself only on the update-on-kvstore path
        # (reference trainer.py:470) — with param_dict pointing at live
        # Parameters, dump_optimizer would embed every weight in the file
        from ..resilience.atomic import atomic_write
        with atomic_write(fname) as f:
            f.write(self._updaters[0].get_states(
                dump_optimizer=bool(self._update_on_kvstore)))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        self._updaters[0].set_states(states)
        self._updaters[0].optimizer = self._optimizer \
            if self._updaters[0].optimizer is None \
            else self._updaters[0].optimizer
        self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)}
        self._fused = None  # the optimizer object may have been replaced

    # -------------------------------------------------- full-state ckpt --
    def save_state(self, run_dir, step=None, epoch=None, keep=5,
                   num_shards=None):
        """Commit the FULL training state to a crash-safe checkpoint
        directory: parameter values, optimizer slots, AMP loss-scaler
        state, global RNG position, and the step counter. Unlike
        ``save_states`` (optimizer pickle only, reference parity), a
        checkpoint written here plus ``restore_state`` resumes a run
        bit-exactly across a process restart.

        ``MXNET_TPU_CKPT_SHARDED`` (or ``num_shards=``) writes the
        parallel per-shard v2 layout; ``MXNET_TPU_CKPT_ASYNC=1`` moves
        serialization off the training thread — the state is snapshotted
        here (step boundary = consistent) and an
        :class:`~mxnet_tpu.resilience.AsyncSaveHandle` is returned
        instead of a path (``ckpt_wait()`` joins; a failed background
        write raises ``CheckpointWriteError`` on the next save/wait).
        Returns None on non-zero ranks."""
        import pickle
        from .. import _rng
        from ..resilience import checkpoint as ckpt
        if not self._kv_initialized:
            self._init_kvstore()
        # keyed by position, not name: gluon name prefixes auto-increment
        # per process (dense0_ vs dense1_), so a restarted process could
        # never match by name; position is what the optimizer state is
        # keyed by anyway
        arrays = {f"param:{i}": p._get_primary()
                  for i, p in enumerate(self._params)
                  if p._data is not None}
        # the updater pickle holds only per-index slot arrays; the
        # Adam-family bias-correction counters live on the Optimizer
        # itself and must ride along or a resumed run diverges
        blob = pickle.dumps({
            "updater": self._updaters[0].get_states(dump_optimizer=False),
            "optimizer": type(self._optimizer).__name__,
            "index_update_count": dict(
                self._optimizer._index_update_count),
            "num_update": self._optimizer.num_update})
        scaler = getattr(self, "_amp_loss_scaler", None)
        extra = {
            "trainer": "gluon",
            "step_count": self._step_count,
            "rng": _rng.get_state(),
            "scaler": scaler.state_dict() if scaler is not None else None,
            "param_names": [p.name for p in self._params],
        }
        # compiled-step bucket warmth rides along so a resumed run pads
        # ragged tails to the same buckets (identical numerics for
        # batch-statistics nets, no cold-bucket recompiles on resume)
        max_batch = max((s._max_batch for s in self._compiled_steps),
                        default=0)
        if max_batch:
            extra["compiled_step"] = {"max_batch": int(max_batch)}
        mgr = ckpt.manager_for(self._ckpt_mgrs, run_dir, keep=keep,
                               num_shards=num_shards)
        return mgr.save(arrays,
                        step=self._step_count if step is None else step,
                        epoch=epoch, extra=extra,
                        blobs={ckpt.TRAINER_FILE: blob})

    def ckpt_wait(self):
        """Join every in-flight async checkpoint save this trainer
        started; drains ALL run dirs before raising the FIRST failure
        (one bad disk must not leave the others' saves unjoined). No-op
        when async checkpointing is off."""
        first = None
        for mgr in self._ckpt_mgrs.values():
            try:
                mgr.wait()
            except BaseException as exc:   # noqa: B036 — InjectedCrash
                if first is None:
                    first = exc
        if first is not None:
            raise first

    def restore_state(self, run_dir):
        """Restore from the newest VALID checkpoint under ``run_dir``
        (corrupt/partial ones are skipped). Returns the manifest, whose
        ``step``/``extra`` tell the training loop where to resume.
        Raises ``mxnet_tpu.error.CheckpointCorruptError`` if nothing
        restorable exists."""
        import pickle
        from .. import _rng, error
        from ..resilience import checkpoint as ckpt
        path, manifest = ckpt.latest_checkpoint(run_dir)
        if path is None:
            raise error.CheckpointCorruptError(
                f"'{run_dir}': no restorable checkpoint found")
        arrays = ckpt.read_arrays(path, manifest)
        for i, p in enumerate(self._params):
            key = f"param:{i}"
            if key in arrays:
                v = arrays[key]
                if p._data is not None and p.shape != v.shape:
                    raise error.InternalError(
                        f"checkpoint '{path}' parameter #{i} "
                        f"('{p.name}') has shape {v.shape}, trainer "
                        f"expects {p.shape}")
                p.set_data(v)
            elif p._data is not None:
                raise error.InternalError(
                    f"checkpoint '{path}' is missing parameter #{i} "
                    f"('{p.name}')")
        blob = pickle.loads(ckpt.read_blob(path, ckpt.TRAINER_FILE,
                                           manifest))
        if not self._kv_initialized:
            self._init_kvstore()
        self._updaters[0].set_states(blob["updater"])
        self._updaters[0].optimizer = self._optimizer
        self._optimizer._index_update_count = {
            int(k): int(v)
            for k, v in blob.get("index_update_count", {}).items()}
        self._optimizer.num_update = int(
            blob.get("num_update", self._optimizer.num_update))
        extra = manifest.get("extra", {})
        self._step_count = int(extra.get("step_count",
                                         manifest.get("step", 0)))
        if extra.get("rng") is not None:
            _rng.set_state(extra["rng"])
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None and extra.get("scaler") is not None:
            scaler.load_state_dict(extra["scaler"])
        # rebuild compiled-step bucket warmth: steps compiled after (or
        # alive across) this restore pad tails to the saved run's
        # buckets instead of rediscovering them cold
        self._restored_step_state = extra.get("compiled_step") or None
        if self._restored_step_state:
            mb = int(self._restored_step_state.get("max_batch", 0) or 0)
            for s in self._compiled_steps:
                s.seed_bucket_state(mb)
        return manifest
