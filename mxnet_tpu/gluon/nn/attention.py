"""Attention layers.

The reference has no attention kernel (SURVEY §5.7) — attention appears
only as model-level example code. This layer family is the TPU-native
fused-attention surface backing the BERT north-star config: projections
are plain MXU matmuls and the core is the registered
``scaled_dot_product_attention`` op (Pallas flash kernel on TPU,
ops/flash_attention.py).
"""
from __future__ import annotations

from ..block import HybridBlock
from .basic_layers import Dense, Dropout

__all__ = ["MultiHeadAttention"]


class MultiHeadAttention(HybridBlock):
    """Multi-head scaled-dot-product attention.

    Inputs: query (B, Tq, units); optional key/value default to query
    (self-attention); optional ``mask`` is an additive row (B, Tk)
    (0 = attend, large negative = drop) — the padding-mask form BERT uses.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False, flash=True, weight_initializer=None,
                 bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads != 0:
            raise ValueError(
                f"units ({units}) must be divisible by num_heads "
                f"({num_heads})")
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._flash = flash
        with self.name_scope():
            common = dict(flatten=False, use_bias=use_bias,
                          weight_initializer=weight_initializer,
                          bias_initializer=bias_initializer)
            self.query_proj = Dense(units, prefix="query_", **common)
            self.key_proj = Dense(units, prefix="key_", **common)
            self.value_proj = Dense(units, prefix="value_", **common)
            self.out_proj = Dense(units, prefix="out_", **common)
            self.dropout_layer = Dropout(dropout) if dropout else None

    def _split_heads(self, x):
        # (B, T, U) -> (B, H, T, D)
        b, t, _ = x.shape
        return x.reshape((b, t, self._num_heads, -1)).transpose(
            (0, 2, 1, 3))

    def _merge_heads(self, x):
        b, h, t, d = x.shape
        return x.transpose((0, 2, 1, 3)).reshape((b, t, h * d))

    def forward(self, query, key=None, value=None, mask=None):
        from ... import ndarray as F
        if key is None:
            key = query
        if value is None:
            value = key
        q = self._split_heads(self.query_proj(query))
        k = self._split_heads(self.key_proj(key))
        v = self._split_heads(self.value_proj(value))
        if mask is not None:
            out = F.scaled_dot_product_attention(
                q, k, v, mask, causal=self._causal, flash=self._flash)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, causal=self._causal, flash=self._flash)
        out = self.out_proj(self._merge_heads(out))
        if self.dropout_layer is not None:
            out = self.dropout_layer(out)
        return out

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise RuntimeError("MultiHeadAttention dispatches in forward()")

    def __repr__(self):
        return (f"MultiHeadAttention(units={self._units}, "
                f"heads={self._num_heads}, causal={self._causal})")
