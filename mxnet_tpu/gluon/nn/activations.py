"""Advanced activation layers.

Reference: python/mxnet/gluon/nn/activations.py (LeakyReLU, PReLU, ELU,
SELU, Swish, GELU).
"""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "GELU"]


class LeakyReLU(HybridBlock):
    """f(x) = max(alpha*x, x) (reference: activations.py:33)."""

    def __init__(self, alpha, **kwargs):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be >= 0."
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"LeakyReLU({self._alpha})"


class PReLU(HybridBlock):
    """Leaky ReLU with learned slope (reference: activations.py:69)."""

    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        if alpha_initializer is None:
            alpha_initializer = init_mod.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha=None):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    """Exponential linear unit (reference: activations.py:109)."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Scaled ELU (reference: activations.py:139)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    """x * sigmoid(beta x) (reference: activations.py:187)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """Gaussian error linear unit (reference: activations.py:162)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")
