"""Neural network layers (reference: python/mxnet/gluon/nn/)."""
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from .activations import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from ..block import Block, HybridBlock, SymbolBlock  # noqa: F401
