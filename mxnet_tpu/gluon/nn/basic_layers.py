"""Basic neural network layers.

Reference: python/mxnet/gluon/nn/basic_layers.py (Sequential, Dense,
Dropout, BatchNorm, Embedding, Flatten, InstanceNorm, LayerNorm, GroupNorm,
Lambda, HybridLambda, Concatenate, HybridConcatenate, Identity). Layers are
thin parameter-holders; all math lives in registered ops (ops/nn.py) and is
compiled by XLA — the bf16/MXU-friendliness comes from the op lowering, not
the layer.
"""
from __future__ import annotations

import numpy as _np

from ... import autograd
from ...context import current_context
from ...ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda", "Concatenate", "HybridConcatenate",
           "Identity"]


class Sequential(Block):
    """Stack of Blocks executed sequentially (reference:
    basic_layers.py:33)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                f"All children of this Sequential layer '{self.prefix}' "
                "are HybridBlocks. Consider using HybridSequential for the "
                "best performance.", stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks, traceable into one XLA program
    (reference: basic_layers.py:102)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        if self._active:
            return HybridBlock.forward(self, x, *args)
        for block in self._children.values():
            x = block(x)
        return x

    def hybrid_forward(self, F, x, *args, **kwargs):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: ``act(dot(x, W^T) + b)``
    (reference: basic_layers.py:162 → FullyConnected op). The weight layout
    (units, in_units) and param names match the reference; note the .params
    file container is this repo's own format (see mxnet_tpu/model.py)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _infer_param_shapes(self, x, *args):
        if self._flatten:
            in_units = int(_np.prod(x.shape[1:]))
        else:
            in_units = x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight=None, bias=None):
        if bias is None:
            act = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        else:
            act = F.FullyConnected(x, weight, bias,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape[1] else None} -> {shape[0]}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Activation(HybridBlock):
    """Activation layer (reference: basic_layers.py:372)."""

    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class Dropout(HybridBlock):
    """Dropout (reference: basic_layers.py:406). Active only in
    autograd.train_mode, like the reference's mode='training'."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with running-stat aux states
    (reference: basic_layers.py:451; op src/operator/nn/batch_norm.cc).

    Aux mutation the TPU way: the op returns batch mean/var; the layer
    updates ``running_mean``/``running_var`` under ``autograd.pause``. In a
    hybridized trace the update is captured as an extra jit output and
    written back post-call (see gluon.block.CachedOp)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._momentum = momentum
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def _infer_param_shapes(self, x, *args):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (ch,)

    def cast(self, dtype):
        if _np.dtype(dtype).name in ("float16", "bfloat16"):
            dtype = "float32"  # norm stats stay fp32 (reference behavior)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma=None, beta=None, running_mean=None,
                       running_var=None):
        training = autograd.is_training()
        if training and not self._use_global_stats:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **self._kwargs)
            with autograd.pause():
                m = self._momentum
                self.running_mean.set_data(running_mean * m + mean * (1 - m))
                self.running_var.set_data(running_var * m + var * (1 - m))
            return out
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return (f"BatchNorm(axis={self._axis}, eps={self._kwargs['eps']}, "
                f"momentum={self._momentum}, "
                f"in_channels={in_channels or None})")


class Embedding(HybridBlock):
    """Index → vector lookup (reference: basic_layers.py:553)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight=None):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Flatten to (batch, -1) (reference: basic_layers.py:618)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class InstanceNorm(HybridBlock):
    """Instance normalization (reference: basic_layers.py:639)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def hybrid_forward(self, F, x, gamma=None, beta=None):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta,
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return (f"InstanceNorm(eps={self._epsilon}, axis={self._axis}, "
                f"in_channels={in_channels})")


class LayerNorm(HybridBlock):
    """Layer normalization (reference: basic_layers.py:729)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def hybrid_forward(self, F, x, gamma=None, beta=None):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return (f"LayerNorm(eps={self._epsilon}, axis={self._axis}, "
                f"in_channels={in_channels})")


class GroupNorm(HybridBlock):
    """Group normalization (reference: basic_layers.py:810)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        ch = x.shape[1]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def hybrid_forward(self, F, x, gamma=None, beta=None):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)

    def __repr__(self):
        return (f"GroupNorm(groups={self._num_groups}, "
                f"eps={self._epsilon})")


class Lambda(Block):
    """Wrap a function or nd-op name as a Block
    (reference: basic_layers.py:893)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            assert hasattr(F, function), \
                f"Function name {function} is not found in ndarray."
            self._func_impl = getattr(F, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    """Hybrid Lambda (reference: basic_layers.py:936)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            assert hasattr(F, function), \
                f"Function name {function} is not found in ndarray."
            self._func = lambda F_, *args: getattr(F_, function)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"


class Concatenate(Sequential):
    """Run children on the same input, concat outputs
    (reference: basic_layers.py 2.0 Concatenate)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as F
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcatenate(HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)

    def forward(self, x, *args):
        if self._active:
            return HybridBlock.forward(self, x, *args)
        from ... import ndarray as F
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Identity mapping (reference 2.0: basic_layers.py Identity)."""

    def hybrid_forward(self, F, x):
        return F.identity(x)
