"""Convolution / pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py (_Conv base, Conv1D/2D/3D,
Conv1DTranspose/…, _Pooling, MaxPool/AvgPool/GlobalMaxPool/GlobalAvgPool,
ReflectionPad2D). Default NCHW/OIHW array layouts mirror the reference
(param shapes/names line up; the .params file container is this repo's
own format — see mxnet_tpu/model.py). ``layout='NHWC'`` keeps activations
channels-last (weights OHWI), ~2x faster for conv nets on TPU.
"""
from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "ReflectionPad2D"]


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    """Base conv layer (reference: conv_layers.py:39)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution",
                 adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        self._layout = layout
        ndim = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        self._ndim = ndim
        self._groups = groups
        with self.name_scope():
            wshape = self._weight_shape(in_channels if in_channels else 0)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _weight_shape(self, in_ch):
        """Weight shape follows the data layout (reference rule: layout with
        N->O, C->I for conv / N->I, C->O for deconv), so NCHW keeps the
        classic OIHW shape while NHWC stores OHWI."""
        kernel = tuple(self._kwargs["kernel"])
        channels_last = self._layout and self._layout[-1] == "C"
        if self._op_name == "Convolution":
            o, i = self._channels, (in_ch // self._groups if in_ch else 0)
        else:  # Deconvolution
            o, i = in_ch, self._channels // self._groups
        return (o,) + kernel + (i,) if channels_last else (o, i) + kernel

    def _infer_param_shapes(self, x, *args):
        c_axis = self._layout.index("C") if self._layout else 1
        in_ch = x.shape[c_axis]
        self.weight.shape = self._weight_shape(in_ch)
        self._in_channels = in_ch

    def hybrid_forward(self, F, x, weight=None, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            act = op(x, weight, **self._kwargs)
        else:
            act = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride}"
        len_kernel_size = len(self._kwargs["kernel"])
        if self._kwargs["pad"] != (0,) * len_kernel_size:
            s += ", padding={pad}"
        if self._kwargs["dilate"] != (1,) * len_kernel_size:
            s += ", dilation={dilate}"
        if self._groups != 1:
            s += ", groups={}".format(self._groups)
        if self.bias is None:
            s += ", bias=False"
        if self.act:
            s += ", {}".format(self.act)
        s += ")"
        shape = self.weight.shape
        channels_last = self._layout and self._layout[-1] == "C"
        in_ch = shape[-1] if channels_last else shape[1]
        return s.format(
            name=self.__class__.__name__,
            mapping="{0} -> {1}".format(in_ch if in_ch else None, shape[0]),
            **self._kwargs)


class Conv1D(_Conv):
    """1-D convolution (reference: conv_layers.py:180)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(
            channels, _tup(kernel_size, 1), _tup(strides, 1),
            _tup(padding, 1), _tup(dilation, 1), groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv2D(_Conv):
    """2-D convolution (reference: conv_layers.py:259)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(
            channels, _tup(kernel_size, 2), _tup(strides, 2),
            _tup(padding, 2), _tup(dilation, 2), groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv3D(_Conv):
    """3-D convolution (reference: conv_layers.py:341)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(
            channels, _tup(kernel_size, 3), _tup(strides, 3),
            _tup(padding, 3), _tup(dilation, 3), groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    """1-D transposed convolution (reference: conv_layers.py:425)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(
            channels, _tup(kernel_size, 1), _tup(strides, 1),
            _tup(padding, 1), _tup(dilation, 1), groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution",
            adj=_tup(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    """2-D transposed convolution (reference: conv_layers.py:509)."""

    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), output_padding=(0, 0), dilation=(1, 1),
                 groups=1, layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(
            channels, _tup(kernel_size, 2), _tup(strides, 2),
            _tup(padding, 2), _tup(dilation, 2), groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution",
            adj=_tup(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    """3-D transposed convolution (reference: conv_layers.py:597)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(
            channels, _tup(kernel_size, 3), _tup(strides, 3),
            _tup(padding, 3), _tup(dilation, 3), groups, layout,
            in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution",
            adj=_tup(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    """Base pooling layer (reference: conv_layers.py:682)."""

    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 global_pool=False, pool_type="max", layout=None,
                 count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return ("{name}(size={kernel}, stride={stride}, padding={pad}, "
                "ceil_mode={ceil_mode})".format(
                    name=self.__class__.__name__,
                    ceil_mode=self._kwargs["pooling_convention"] == "full",
                    **self._kwargs))


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(
            _tup(pool_size, 1), strides if strides is None
            else _tup(strides, 1), _tup(padding, 1), ceil_mode, False,
            "max", layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(
            _tup(pool_size, 2), strides if strides is None
            else _tup(strides, 2), _tup(padding, 2), ceil_mode, False,
            "max", layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(
            _tup(pool_size, 3), strides if strides is None
            else _tup(strides, 3), _tup(padding, 3), ceil_mode, False,
            "max", layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(
            _tup(pool_size, 1), strides if strides is None
            else _tup(strides, 1), _tup(padding, 1), ceil_mode, False,
            "avg", layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(
            _tup(pool_size, 2), strides if strides is None
            else _tup(strides, 2), _tup(padding, 2), ceil_mode, False,
            "avg", layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(
            _tup(pool_size, 3), strides if strides is None
            else _tup(strides, 3), _tup(padding, 3), ceil_mode, False,
            "avg", layout, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, True, "max", layout,
                         **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "max", layout,
                         **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "max",
                         layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, True, "avg", layout,
                         **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "avg", layout,
                         **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "avg",
                         layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H/W (reference: conv_layers.py:1126)."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
