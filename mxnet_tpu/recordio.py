"""RecordIO: record-structured binary container.

Reference: python/mxnet/recordio.py:36 (MXRecordIO/MXIndexedRecordIO over
the dmlc-core C++ reader) + dmlc-core recordio framing. The binary FORMAT
is kept bit-compatible (kMagic 0xced7230a, cflag<<29|len header, 4-byte
alignment, IRHeader struct) so .rec/.idx files interchange with the
reference's im2rec output; the implementation is pure Python + cv2 — on
TPU the decode path feeds host staging buffers, there is no GPU decode to
integrate with.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as _np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xced7230a
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential .rec reader/writer (reference: recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.is_open = False
        self.open()

    def open(self):
        self._native = None
        if self.flag == "w":
            self.fhandle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fhandle = open(self.uri, "rb")
            self.writable = False
            # fast path: the C++ reader (mxnet_tpu/native) parses and
            # assembles records off the GIL; transparently falls back to
            # the pure-Python parser when no toolchain is available
            try:
                from .native import NativeRecordReader
                self._native = NativeRecordReader(self.uri)
            except Exception:
                self._native = None
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d.pop("fhandle", None)
        d.pop("_native", None)
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        is_open = d.get("is_open", False)
        self.is_open = False
        if is_open:
            self.open()

    def _check_pid(self, allow_reset=False):
        # forked workers must reopen their own handle (reference:
        # recordio.py _check_pid — DataLoader worker semantics)
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise RuntimeError("Forbidden operation in a forked "
                                   "process")

    def close(self):
        if not self.is_open:
            return
        if getattr(self, "_native", None) is not None:
            self._native.close()
            self._native = None
        self.fhandle.close()
        self.is_open = False
        self.pid = None

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        """Append one record (reference: recordio.py:180; framing
        dmlc-core include/dmlc/recordio.h). Payloads containing the magic
        word are split into multipart records (cflag 1=begin, 2=middle,
        3=end) exactly like the dmlc writer, so files interchange."""
        assert self.writable
        self._check_pid(allow_reset=False)
        magic_bytes = struct.pack("<I", _kMagic)
        # split at aligned occurrences of the magic word (dmlc scans in
        # 4-byte steps)
        parts = []
        start = 0
        for off in range(0, len(buf) - 3, 4):
            if buf[off:off + 4] == magic_bytes:
                parts.append(buf[start:off])
                start = off + 4
        parts.append(buf[start:])
        for i, part in enumerate(parts):
            if len(parts) == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == len(parts) - 1:
                cflag = 3
            else:
                cflag = 2
            self.fhandle.write(magic_bytes)
            self.fhandle.write(struct.pack(
                "<I", (cflag << 29) | len(part)))
            self.fhandle.write(part)
            pad = (4 - (len(part) % 4)) % 4
            if pad:
                self.fhandle.write(b"\x00" * pad)

    def _read_chunk(self):
        header = self.fhandle.read(8)
        if len(header) < 8:
            return None, None
        magic, lrec = struct.unpack("<II", header)
        assert magic == _kMagic, "invalid record magic"
        cflag = lrec >> 29
        length = lrec & ((1 << 29) - 1)
        buf = self.fhandle.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.fhandle.read(pad)
        return cflag, buf

    def read(self):
        """Read next record or None (reference: recordio.py:210).
        Multipart records are rejoined with the magic word re-inserted at
        the split points (dmlc-core ReadRecord semantics)."""
        assert not self.writable
        self._check_pid(allow_reset=True)
        if self._native is not None:
            return self._native.read()
        cflag, buf = self._read_chunk()
        if buf is None:
            return None
        if cflag == 0:
            return buf
        assert cflag == 1, f"unexpected continuation flag {cflag}"
        magic_bytes = struct.pack("<I", _kMagic)
        parts = [buf]
        while True:
            cflag, buf = self._read_chunk()
            assert buf is not None, "truncated multipart record"
            parts.append(buf)
            if cflag == 3:
                break
            assert cflag == 2, f"unexpected continuation flag {cflag}"
        return magic_bytes.join(parts)

    def tell(self):
        if getattr(self, "_native", None) is not None and not self.writable:
            return self._native.tell()
        return self.fhandle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """.rec + .idx random access (reference: recordio.py:247)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self._check_pid(allow_reset=True)
        if self._native is not None:
            self._native.seek(self.idx[idx])
        else:
            self.fhandle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.keys.append(key)
        self.idx[key] = pos


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
"""Record header (reference: recordio.py:344 IRHeader)."""


def pack(header, s):
    """Pack a header + byte payload (reference: recordio.py:355)."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (_np.ndarray, list, tuple)):
        label = _np.asarray(label, dtype=_np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                       header.id2) + s


def unpack(s):
    """Unpack to (header, payload) (reference: recordio.py:389)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _np.frombuffer(s[:header.flag * 4], dtype=_np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=1):
    """Unpack a packed image record (reference: recordio.py:417)."""
    import cv2
    header, s = unpack(s)
    img = cv2.imdecode(_np.frombuffer(s, dtype=_np.uint8), iscolor)
    if img is not None and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (reference: recordio.py:453)."""
    import cv2
    if img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_RGB2BGR)
    encode_params = None
    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())
