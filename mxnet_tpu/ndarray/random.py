"""``nd.random``: random sampling namespace.

Reference: python/mxnet/ndarray/random.py. Scalar-parameter calls route to
the ``_random_*`` ops; NDArray-parameter calls route to ``_sample_*``
(per-element distribution parameters), matching the reference dispatch.
"""
from __future__ import annotations

from ..ops.invoke import apply_op
from .ndarray import NDArray
from ..context import current_context
from .. import _rng

__all__ = ["uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial",
           "multinomial", "randint", "shuffle", "seed", "bernoulli"]


def seed(seed_state, ctx="all"):
    _rng.seed(seed_state)


def _place(res, ctx):
    if ctx is None or res is None:
        return res
    if isinstance(res, tuple):
        return tuple(r.as_in_context(ctx) for r in res)
    return res.as_in_context(ctx)


def _dispatch(scalar_op, sample_op, scalar_params, arr_args, shape, dtype,
              ctx, out):
    if any(isinstance(a, NDArray) for a in arr_args):
        # per-element distribution parameters: broadcast scalars/arrays to a
        # common shape first (reference raises on mixed types; we accept and
        # broadcast, which is a superset)
        import numpy as _np
        import jax.numpy as jnp
        datas = [a._data if isinstance(a, NDArray) else jnp.asarray(a, jnp.float32)
                 for a in arr_args]
        common = _np.broadcast_shapes(*[tuple(d.shape) for d in datas])
        arrs = [NDArray(jnp.broadcast_to(d, common)) for d in datas]
        res = apply_op(sample_op, arrs, {"shape": shape, "dtype": dtype},
                       out=out)
        return _place(res, ctx)
    params = dict(scalar_params)
    params.update({"shape": shape or (1,), "dtype": dtype})
    return _place(apply_op(scalar_op, [], params, out=out), ctx)


def uniform(low=0, high=1, shape=None, dtype="float32", ctx=None, out=None):
    return _dispatch("_random_uniform", "_sample_uniform",
                     {"low": low, "high": high}, (low, high), shape, dtype,
                     ctx, out)


def normal(loc=0, scale=1, shape=None, dtype="float32", ctx=None, out=None):
    return _dispatch("_random_normal", "_sample_normal",
                     {"loc": loc, "scale": scale}, (loc, scale), shape,
                     dtype, ctx, out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape or (1,), dtype, ctx)


def gamma(alpha=1, beta=1, shape=None, dtype="float32", ctx=None, out=None):
    return _dispatch("_random_gamma", "_sample_gamma",
                     {"alpha": alpha, "beta": beta}, (alpha, beta), shape,
                     dtype, ctx, out)


def exponential(scale=1, shape=None, dtype="float32", ctx=None, out=None):
    return _place(apply_op("_random_exponential", [],
                           {"lam": 1.0 / scale, "shape": shape or (1,),
                            "dtype": dtype}, out=out), ctx)


def poisson(lam=1, shape=None, dtype="float32", ctx=None, out=None):
    return _place(apply_op("_random_poisson", [],
                           {"lam": lam, "shape": shape or (1,),
                            "dtype": dtype}, out=out), ctx)


def negative_binomial(k=1, p=1, shape=None, dtype="float32", ctx=None,
                      out=None):
    return _place(apply_op("_random_negative_binomial", [],
                           {"k": k, "p": p, "shape": shape or (1,),
                            "dtype": dtype}, out=out), ctx)


def generalized_negative_binomial(mu=1, alpha=1, shape=None, dtype="float32",
                                  ctx=None, out=None):
    return _place(apply_op("_random_generalized_negative_binomial", [],
                           {"mu": mu, "alpha": alpha, "shape": shape or (1,),
                            "dtype": dtype}, out=out), ctx)


def multinomial(data, shape=None, get_prob=False, out=None, dtype="int32"):
    return apply_op("_sample_multinomial", [data],
                    {"shape": shape, "get_prob": get_prob, "dtype": dtype},
                    out=out)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    return _place(apply_op("_random_randint", [],
                           {"low": low, "high": high, "shape": shape or (1,),
                            "dtype": dtype}, out=out), ctx)


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, out=None):
    return _place(apply_op("_sample_bernoulli", [],
                           {"prob": prob, "shape": shape or (1,),
                            "dtype": dtype}, out=out), ctx)


def shuffle(data, out=None):
    return apply_op("_shuffle", [data], {}, out=out)
