"""nd.contrib — control-flow operators (+ contrib op aliases).

Reference: src/operator/control_flow.cc (_foreach :1089, _while_loop
:1150, _cond :1211) exposed through python/mxnet/ndarray/contrib.py
(foreach :68, while_loop :171, cond :302). There the loop body becomes a
sub-CachedOp executed by a stateful C++ operator; here the body is
traced straight into ``lax.scan`` / ``lax.cond`` — the natural XLA
control flow — and the whole loop lands on the autograd tape as ONE node
whose backward is jax's scan/cond vjp. Inside ``hybridize``/``jit`` the
loop compiles instead of unrolling.

TPU-native deviation (documented): ``while_loop`` lowers to a
fixed-trip masked ``lax.scan`` over ``max_iterations`` — XLA cannot
reverse-differentiate a dynamic-trip ``lax.while_loop``, and masked
fixed-trip loops are the standard TPU recipe. Slots after loop exit are
zero-filled (the reference leaves them undefined).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.invoke import apply_fn

__all__ = ["foreach", "while_loop", "cond"]


def _aslist(x):
    if x is None:
        return [], True
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def _unwrap(nd_list):
    return tuple(x._data for x in nd_list)


def _ndarray_cls():
    from . import NDArray
    return NDArray


def foreach(body, data, init_states):
    """Iterate ``body`` over the leading axis of ``data``
    (reference: ndarray/contrib.py:68 foreach, control_flow.cc:1089).

    body(data_t, states) -> (outputs_t, new_states); returns
    (stacked outputs, final states). data/init_states/outputs may each
    be a single NDArray or a list.
    """
    NDArray = _ndarray_cls()
    datas, data_single = _aslist(data)
    states0, state_single = _aslist(init_states)
    nd_, ns_ = len(datas), len(states0)
    meta = {}

    def pure(*args):
        ds, ss = args[:nd_], args[nd_:]

        def step(carry, xs):
            x_nd = [NDArray(x) for x in xs]
            s_nd = [NDArray(c) for c in carry]
            if ns_ == 0:          # stateless loop: body sees states=None
                s_arg = None
            else:
                s_arg = s_nd[0] if state_single else s_nd
            outs, new_states = body(x_nd[0] if data_single else x_nd,
                                    s_arg)
            outs_l, meta["out_single"] = _aslist(outs)
            ns_l, _ = _aslist(new_states)
            meta["nout"] = len(outs_l)
            return _unwrap(ns_l), _unwrap(outs_l)

        carry, ys = lax.scan(step, tuple(ss), tuple(ds))
        return tuple(ys) + tuple(carry)

    res = apply_fn(pure, datas + states0)
    res = (res,) if not isinstance(res, tuple) else tuple(res)
    outs = list(res[:meta["nout"]])
    fin = list(res[meta["nout"]:])
    if ns_ == 0:
        fin = None
    elif state_single:
        fin = fin[0]
    return (outs[0] if meta["out_single"] else outs, fin)


def while_loop(cond, func, loop_vars, max_iterations):
    """Bounded while loop (reference: ndarray/contrib.py:171 while_loop,
    control_flow.cc:1150).

    cond(*loop_vars) -> boolean scalar; func(*loop_vars) ->
    (step_output(s), new_loop_vars). Returns (stacked outputs, final
    loop_vars); outputs beyond the exit step are zeros. Runs as a
    fixed-trip masked scan (see module docstring).
    """
    NDArray = _ndarray_cls()
    lvars, _ = _aslist(loop_vars)
    nvars = len(lvars)
    meta = {}

    def pure(*args):
        def step(carry, _):
            vars_j, done = carry
            v_nd = [NDArray(v) for v in vars_j]
            alive = jnp.logical_and(
                jnp.logical_not(done),
                jnp.asarray(cond(*v_nd)._data, bool).reshape(()))
            outs, new_vars = func(*v_nd)
            outs_l, meta["out_single"] = _aslist(outs)
            nv_l, _ = _aslist(new_vars)
            meta["nout"] = len(outs_l)
            # masked commit: state/output only advance while alive
            kept = tuple(jnp.where(alive, nv._data, v)
                         for nv, v in zip(nv_l, vars_j))
            ys = tuple(jnp.where(alive, o._data,
                                 jnp.zeros_like(o._data))
                       for o in outs_l)
            return (kept, jnp.logical_not(alive)), ys

        (final_vars, _), ys = lax.scan(
            step, (tuple(args), jnp.asarray(False)), None,
            length=max_iterations)
        return tuple(ys) + tuple(final_vars)

    res = apply_fn(pure, lvars)
    res = (res,) if not isinstance(res, tuple) else tuple(res)
    outs = list(res[:meta["nout"]])
    fin = list(res[meta["nout"]:])
    return (outs[0] if meta["out_single"] else outs,
            fin if not isinstance(loop_vars, NDArray) else fin[0])


def cond(pred, then_func, else_func, inputs):
    """Conditional execution (reference: ndarray/contrib.py:302 cond,
    control_flow.cc:1211): pred(*inputs) picks then_func(*inputs) or
    else_func(*inputs); both branches are traced (XLA requirement) but
    only one executes. Branch outputs must match in shape/dtype."""
    NDArray = _ndarray_cls()
    ins, _ = _aslist(inputs)
    meta = {}

    def pure(*args):
        a_nd = [NDArray(a) for a in args]
        p = jnp.asarray(pred(*a_nd)._data, bool).reshape(())

        def mk(branch):
            def run(operands):
                outs = branch(*[NDArray(o) for o in operands])
                outs_l, meta["out_single"] = _aslist(outs)
                return _unwrap(outs_l)
            return run

        out = lax.cond(p, mk(then_func), mk(else_func), args)
        # single outputs stay bare: the tape hands single-output nodes a
        # bare cotangent, which must match this function's output tree
        return out[0] if len(out) == 1 else out

    res = apply_fn(pure, ins)
    res = (res,) if not isinstance(res, tuple) else tuple(res)
    outs = list(res)
    return outs[0] if meta["out_single"] else outs


# contrib-namespaced aliases of registered ops (reference: many
# _contrib_* ops are reachable as nd.contrib.<name>)
def __getattr__(name):
    from .. import ndarray as _nd
    for target in (f"_contrib_{name}", name):
        if hasattr(_nd, target):
            return getattr(_nd, target)
    raise AttributeError(f"nd.contrib has no attribute {name!r}")
