"""NDArray: imperative array facade over ``jax.Array``.

TPU-native re-design of the reference NDArray
(reference: include/mxnet/ndarray.h:82, src/ndarray/ndarray.cc). The
reference NDArray is a ref-counted mutable Chunk plus an engine variable;
asynchronous ordering (write-after-read etc.) is enforced by the dependency
engine. Here the backing store is an immutable ``jax.Array``: "mutation"
rebinds ``_data`` to a new buffer, which is race-free by construction —
any already-recorded autograd closure or in-flight XLA computation holds the
old value. ``wait_to_read`` maps to ``block_until_ready`` (the reference's
``WaitToRead``, include/mxnet/ndarray.h:374).

Async semantics match the reference: ops return immediately (JAX async
dispatch), Python only blocks on ``asnumpy()``/``wait_to_read()``.
"""
from __future__ import annotations

import functools
import operator
from typing import Optional

import numpy as _np
import jax
import jax.numpy as jnp

from .. import autograd
from ..base import MXNetError, dtype_np, dtype_name
from ..context import Context, current_context
from ..ops.invoke import apply_fn, apply_op, as_jax

__all__ = ["NDArray"]


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


class NDArray:
    """An imperative, context-aware n-dimensional array.

    Wraps either a concrete ``jax.Array`` or (inside ``jit`` tracing of
    hybridized blocks) a JAX tracer — the whole eager API is trace-
    transparent, which is how HybridBlock/CachedOp compilation works without
    a separate Symbol path.
    """

    __slots__ = ("_data", "_grad", "_grad_req", "_ag_slot", "__weakref__")

    # numpy should defer to us in mixed expressions
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if not (isinstance(data, jax.Array) or _is_tracer(data)):
            data = jnp.asarray(data, dtype=dtype_np(dtype) if dtype else None)
        elif dtype is not None and data.dtype != dtype_np(dtype):
            data = data.astype(dtype_np(dtype))
        if ctx is not None and not _is_tracer(data):
            data = jax.device_put(data, ctx.jax_device)
        self._data = data
        self._grad = None
        self._grad_req = "null"
        self._ag_slot = None

    # ------------------------------------------------------------ basics --
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return functools.reduce(operator.mul, self.shape, 1)

    @property
    def context(self) -> Context:
        if _is_tracer(self._data):
            return current_context()
        try:
            dev = next(iter(self._data.devices()))
        except Exception:
            return current_context()
        from ..context import device as _device
        return _device(dev)

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return self.transpose()

    def __repr__(self):
        if _is_tracer(self._data):
            return f"<NDArray tracer {self.shape} {dtype_name(self.dtype)}>"
        return (f"\n{_np.asarray(self.asnumpy())}\n"
                f"<NDArray {'x'.join(map(str, self.shape))} "
                f"@{self.context}>")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple "
                             "elements is ambiguous.")
        return bool(self.asnumpy().item())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        v = self.asscalar()
        if isinstance(v, (bool, _np.bool_)) or \
                not isinstance(v, (int, _np.integer)):
            raise TypeError("only integer arrays can be used as an index")
        return int(v)

    # ------------------------------------------------------- sync points --
    def asnumpy(self) -> _np.ndarray:
        """Blocking device→host copy (reference: NDArray::SyncCopyToCPU)."""
        return _np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().item()

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def wait_to_read(self):
        """Reference: NDArray::WaitToRead → jax block_until_ready."""
        if not _is_tracer(self._data):
            self._data.block_until_ready()

    wait_to_write = wait_to_read

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype else a

    # ------------------------------------------------------------ dtypes --
    def astype(self, dtype, copy=True):
        d = dtype_np(dtype)
        if not copy and self.dtype == d:
            return self
        return apply_fn(lambda x: x.astype(d), [self])

    def cast(self, dtype):
        return self.astype(dtype)

    # ----------------------------------------------------------- copying --
    def copy(self):
        return apply_fn(lambda x: x + 0, [self])

    def copyto(self, other):
        """Copy into an existing array or to a context
        (reference: NDArray::CopyTo / SyncCopyFromNDArray)."""
        if isinstance(other, NDArray):
            # copy INTO the destination's context (reference NDArray::CopyTo
            # keeps the destination device — this is the host→device
            # parameter-loading idiom)
            dst_ctx = other.context
            other._data = jax.device_put(
                jnp.asarray(self._data, dtype=other.dtype),
                dst_ctx.jax_device)
            return other
        if isinstance(other, Context):
            return NDArray(self._data, ctx=other)
        raise TypeError(f"copyto does not support type {type(other)}")

    def as_in_context(self, ctx: Context):
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device))

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def detach(self):
        out = NDArray(self._data)
        return out

    # ----------------------------------------------------------- autograd --
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate a gradient buffer updated by ``autograd.backward``
        (reference: python/mxnet/ndarray/ndarray.py attach_grad)."""
        self._grad = NDArray(jnp.zeros(self.shape, self.dtype))
        self._grad_req = grad_req
        if self._ag_slot is None:
            self._ag_slot = autograd.new_slot()
        autograd.register_leaf(self._ag_slot, self, grad_req)

    @property
    def grad(self):
        return self._grad

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ----------------------------------------------------------- indexing --
    def _canon_key(self, key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(k._data if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        key = self._canon_key(key)
        if isinstance(key, (jax.Array, _np.ndarray)) or _is_tracer(key):
            # advanced indexing with an array operand — keep it an op input
            karr = NDArray(key) if not isinstance(key, NDArray) else key
            if karr.dtype == _np.bool_:
                # boolean mask: dynamic output shape; must leave trace-land
                mask = _np.asarray(jax.device_get(key))
                return apply_fn(lambda x: x[mask], [self])
            return apply_fn(lambda x, k: x[k.astype(jnp.int32)], [self, karr])
        return apply_fn(lambda x: x[key], [self])

    def __setitem__(self, key, value):
        key = self._canon_key(key)
        v = as_jax(value)
        if isinstance(key, slice) and key == slice(None):
            # x[:] = v — full overwrite preserving shape/dtype
            self._data = jnp.broadcast_to(jnp.asarray(v, dtype=self.dtype),
                                          self.shape)
        else:
            self._data = self._data.at[key].set(
                jnp.asarray(v, dtype=self.dtype) if not _np.isscalar(v) else v)

    # ---------------------------------------------------------- arithmetic --
    def _binop(self, other, opname, scalar_op):
        if isinstance(other, NDArray):
            return apply_op(opname, [self, other])
        if _is_tracer(other) or isinstance(other, (jax.Array, _np.ndarray)):
            return apply_op(opname, [self, NDArray(other)])
        return apply_op(scalar_op, [self], {"scalar": float(other)})

    def _rbinop(self, other, opname, scalar_op):
        if isinstance(other, (jax.Array, _np.ndarray)) or _is_tracer(other):
            return apply_op(opname, [NDArray(other), self])
        return apply_op(scalar_op, [self], {"scalar": float(other)})

    def __add__(self, o):
        return self._binop(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._rbinop(o, "broadcast_sub", "_rminus_scalar")

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._rbinop(o, "broadcast_div", "_rdiv_scalar")

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._rbinop(o, "broadcast_mod", "_rmod_scalar")

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._rbinop(o, "broadcast_power", "_rpower_scalar")

    def __neg__(self):
        return apply_op("negative", [self])

    def __abs__(self):
        return apply_op("abs", [self])

    def __eq__(self, o):  # noqa: D105  (mx semantics: elementwise)
        if o is None:
            return False
        return self._binop(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__  # identity hash like the reference handle

    def __iadd__(self, o):
        r = self.__add__(o)
        self._data = r._data
        return self

    def __isub__(self, o):
        r = self.__sub__(o)
        self._data = r._data
        return self

    def __imul__(self, o):
        r = self.__mul__(o)
        self._data = r._data
        return self

    def __itruediv__(self, o):
        r = self.__truediv__(o)
        self._data = r._data
        return self

    # --------------------------------------------------- method op mirrors --
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return apply_op("reshape", [self], {"shape": tuple(shape)})

    def reshape_like(self, other):
        return apply_op("reshape_like", [self, other])

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return apply_op("transpose", [self], {"axes": axes or None})

    def swapaxes(self, dim1, dim2):
        return apply_op("swapaxes", [self], {"dim1": dim1, "dim2": dim2})

    def flatten(self):
        return apply_op("flatten", [self])

    def expand_dims(self, axis):
        return apply_op("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return apply_op("squeeze", [self], {"axis": axis})

    def broadcast_to(self, shape):
        return apply_op("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return apply_op("broadcast_like", [self, other])

    def tile(self, reps):
        return apply_op("tile", [self], {"reps": tuple(reps) if
                                         isinstance(reps, (tuple, list)) else (reps,)})

    def repeat(self, repeats, axis=None):
        return apply_op("repeat", [self], {"repeats": repeats, "axis": axis})

    def flip(self, axis):
        return apply_op("flip", [self], {"axis": axis})

    def clip(self, a_min=None, a_max=None):
        return apply_op("clip", [self], {"a_min": a_min, "a_max": a_max})

    def slice_axis(self, axis, begin, end):
        return apply_op("slice_axis", [self],
                        {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return apply_op("take", [self, indices], {"axis": axis, "mode": mode})

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return apply_op("one_hot", [self], {"depth": depth,
                                            "on_value": on_value,
                                            "off_value": off_value,
                                            "dtype": dtype})

    def _reduce(self, opname, axis=None, keepdims=False, **kw):
        params = {"axis": axis, "keepdims": keepdims}
        params.update(kw)
        return apply_op(opname, [self], params)

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return apply_op("norm", [self], {"ord": ord, "axis": axis,
                                         "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return self._reduce("argmax", axis, keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._reduce("argmin", axis, keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        return apply_op("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return apply_op("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return apply_op("topk", [self], {"axis": axis, "k": k,
                                         "ret_typ": ret_typ,
                                         "is_ascend": is_ascend})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return apply_op("dot", [self, other],
                        {"transpose_a": transpose_a, "transpose_b": transpose_b})

    def abs(self):
        return apply_op("abs", [self])

    def sqrt(self):
        return apply_op("sqrt", [self])

    def square(self):
        return apply_op("square", [self])

    def exp(self):
        return apply_op("exp", [self])

    def log(self):
        return apply_op("log", [self])

    def relu(self):
        return apply_op("relu", [self])

    def sigmoid(self):
        return apply_op("sigmoid", [self])

    def tanh(self):
        return apply_op("tanh", [self])

    def softmax(self, axis=-1):
        return apply_op("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return apply_op("log_softmax", [self], {"axis": axis})

    def zeros_like(self):
        return apply_op("zeros_like", [self])

    def ones_like(self):
        return apply_op("ones_like", [self])

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return apply_op("split", [self], {"num_outputs": num_outputs,
                                          "axis": axis,
                                          "squeeze_axis": squeeze_axis})

    def pad(self, mode, pad_width, constant_value=0):
        return apply_op("pad", [self], {"mode": mode,
                                        "pad_width": tuple(pad_width),
                                        "constant_value": constant_value})

    # --------------------------------------------------------------- misc --
    def __reduce__(self):
        # pickling: used by Updater.get_states / DataLoader worker IPC.
        # Context is intentionally not pickled (a checkpoint restored on a
        # different host lands on its default device, like the reference's
        # save/load default-ctx behavior).
        return (NDArray, (self.asnumpy(),))

    def as_np_ndarray(self):
        from ..numpy import ndarray as np_ndarray
        out = np_ndarray(self._data)
        out._ag_slot = self._ag_slot
        out._grad = self._grad
        return out

    def to_dlpack_for_read(self):
        return jax.dlpack.to_dlpack(self._data)

    to_dlpack_for_write = to_dlpack_for_read
