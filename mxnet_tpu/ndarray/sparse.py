"""Sparse NDArray storage types.

Reference: include/mxnet/ndarray.h:63-65 (kDefaultStorage, kRowSparseStorage,
kCSRStorage), python/mxnet/ndarray/sparse.py. XLA has no native sparse
tensors, so the TPU design keeps the *API* (stype, indices/data accessors,
cast_storage, sparse row_sparse_pull semantics in kvstore) over an explicit
index+values representation; compute densifies at op boundaries. This is the
"explicit gather/scatter" strategy called out in SURVEY.md §7 hard-parts.
Gradient row-sparsity (Embedding sparse_grad) is handled structurally by the
optimizer taking the row-index fast path when it sees a RowSparseNDArray.
"""
from __future__ import annotations

import numpy as _np
import jax.numpy as jnp

from .ndarray import NDArray

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "cast_storage"]


class RowSparseNDArray(NDArray):
    """Row-sparse array: (indices, values) over the leading axis."""

    __slots__ = ("_indices", "_values")

    def __init__(self, values, indices, shape):
        vals = values._data if isinstance(values, NDArray) else jnp.asarray(values)
        idx = indices._data if isinstance(indices, NDArray) else \
            jnp.asarray(indices, jnp.int32)
        dense = jnp.zeros(tuple(shape), vals.dtype).at[idx].set(vals)
        super().__init__(dense)
        self._indices = idx
        self._values = vals

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def data(self):
        return NDArray(self._values)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        return self


class CSRNDArray(NDArray):
    """Compressed sparse row matrix."""

    __slots__ = ("_indptr", "_indices", "_values")

    def __init__(self, data, indptr, indices, shape):
        vals = _np.asarray(data)
        ip = _np.asarray(indptr, _np.int32)
        ind = _np.asarray(indices, _np.int32)
        dense = _np.zeros(tuple(shape), vals.dtype)
        for r in range(shape[0]):
            dense[r, ind[ip[r]:ip[r + 1]]] = vals[ip[r]:ip[r + 1]]
        super().__init__(jnp.asarray(dense))
        self._indptr = jnp.asarray(ip)
        self._indices = jnp.asarray(ind)
        self._values = jnp.asarray(vals)

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self):
        return NDArray(self._indptr)

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def data(self):
        return NDArray(self._values)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        return self


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = arg1
        return RowSparseNDArray(values, indices, shape)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(dense[nz], nz, dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indptr, indices, shape)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    import numpy as np
    indptr = [0]
    indices = []
    data = []
    for row in dense:
        nz = np.nonzero(row)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(np.asarray(data, dense.dtype), indptr, indices,
                      dense.shape)


def cast_storage(arr, stype):
    """Reference: src/operator/tensor/cast_storage.cc."""
    if stype == "default":
        return NDArray(arr._data)
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise ValueError(stype)
