"""Sparse NDArray storage types.

Reference: include/mxnet/ndarray.h:63-65 (kDefaultStorage,
kRowSparseStorage, kCSRStorage), python/mxnet/ndarray/sparse.py,
src/operator/tensor/cast_storage.cc, dot.cc.

TPU-native design: XLA has no native sparse tensors, so sparsity here is
*structural* — explicit (indices, values) pairs plus gather/scatter/
segment-sum compute (the SURVEY §7 strategy). What is genuinely sparse:

- storage: RowSparseNDArray/CSRNDArray hold only indices+values;
  densification is lazy (first `_data` touch) and cached, so sparse
  gradients and kvstore rows never materialize the full array unless a
  dense op is applied to them.
- Embedding sparse_grad=True backward produces a RowSparseNDArray of
  (touched row ids, output cotangents) — no (vocab, dim) scatter
  (reference: src/operator/tensor/indexing_op.cc EmbeddingOpBackward
  with kRowSparseStorage).
- optimizer lazy updates: sgd/adam touch only the rows present in a
  row-sparse grad (reference: src/operator/optimizer_op.cc
  SGDUpdateRspImpl "lazy update").
- dot(csr, dense): one gather + segment-sum — a real CSR SpMM that
  jits (reference: src/operator/tensor/dot-inl.h DotCsrDnsDns).

Generic ops on sparse arrays fall back to the cached dense form —
matching the reference's FallBackCompute / storage-fallback behaviour.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from .ndarray import NDArray

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "retain",
           "dot", "add", "zeros"]


class BaseSparseNDArray(NDArray):
    """Common lazy-densification machinery.

    The base NDArray keeps its buffer in the ``_data`` slot; subclasses
    shadow that slot with a property so the whole eager API works on
    sparse arrays (densifying on demand), while sparse-aware paths
    (optimizers, kvstore, sparse.dot) read ``indices``/``data`` and never
    trigger it.
    """

    __slots__ = ("_dense",)

    def _init_base(self):
        # bypass NDArray.__init__ (no dense buffer yet)
        self._dense = None
        self._grad = None
        self._grad_req = "null"
        self._ag_slot = None

    def _densify(self):
        raise NotImplementedError

    @property
    def _data(self):
        if self._dense is None:
            self._dense = self._densify()
        return self._dense

    @_data.setter
    def _data(self, value):
        # an in-place op rebinding the buffer makes the cached dense form
        # authoritative (the array is no longer structurally sparse)
        self._dense = value

    @property
    def densified(self):
        """True once the dense form has been materialized."""
        return self._dense is not None

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        out = 1
        for s in self.shape:
            out *= s
        return out

    def wait_to_read(self):
        (self._values if hasattr(self, "_values") else self._data)\
            .block_until_ready()
        return self


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array: ``values[i]`` is row ``indices[i]`` of a dense
    array of shape ``shape``; all other rows are zero."""

    __slots__ = ("_indices", "_values", "_sshape")

    def __init__(self, values, indices, shape=None):
        vals = values._data if isinstance(values, NDArray) \
            else jnp.asarray(values)
        idx = indices._data if isinstance(indices, NDArray) \
            else jnp.asarray(indices, jnp.int32)
        if idx.dtype not in (jnp.int32, jnp.int64):
            idx = idx.astype(jnp.int32)
        if shape is None:
            first = int(idx.max()) + 1 if idx.size else 0
            shape = (first,) + tuple(vals.shape[1:])
        self._init_base()
        self._indices = idx
        self._values = vals
        self._sshape = tuple(int(s) for s in shape)

    def _densify(self):
        return jnp.zeros(self._sshape, self._values.dtype)\
            .at[self._indices].add(self._values)

    # ------------------------------------------------------------ api --
    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._sshape

    @property
    def dtype(self):
        return _np.dtype(self._values.dtype)

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def data(self):
        return NDArray(self._values)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        if stype == "row_sparse":
            return self
        if stype == "csr" and len(self._sshape) == 2:
            return cast_storage(NDArray(self._data), "csr")
        raise ValueError(f"cannot cast row_sparse to {stype}")

    def retain(self, row_ids):
        return retain(self, row_ids)

    def copyto(self, other):
        from ..context import Context
        if isinstance(other, Context):
            return RowSparseNDArray(self._values, self._indices,
                                    self._sshape)
        return NDArray.copyto(NDArray(self._data), other)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {self._sshape} "
                f"nnz-rows={int(self._indices.shape[0])}>")


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix."""

    __slots__ = ("_indptr", "_indices", "_values", "_sshape")

    def __init__(self, data, indptr, indices, shape):
        vals = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        ip = indptr._data if isinstance(indptr, NDArray) \
            else jnp.asarray(indptr, jnp.int32)
        ind = indices._data if isinstance(indices, NDArray) \
            else jnp.asarray(indices, jnp.int32)
        self._init_base()
        self._indptr = ip.astype(jnp.int32)
        self._indices = ind.astype(jnp.int32)
        self._values = vals
        self._sshape = tuple(int(s) for s in shape)

    def _row_ids(self):
        """Per-nonzero row id, from the indptr run lengths."""
        counts = jnp.diff(self._indptr)
        return jnp.repeat(jnp.arange(self._sshape[0], dtype=jnp.int32),
                          counts, total_repeat_length=self._values.shape[0])

    def _densify(self):
        rows = self._row_ids()
        return jnp.zeros(self._sshape, self._values.dtype)\
            .at[rows, self._indices].add(self._values)

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._sshape

    @property
    def dtype(self):
        return _np.dtype(self._values.dtype)

    @property
    def indptr(self):
        return NDArray(self._indptr)

    @property
    def indices(self):
        return NDArray(self._indices)

    @property
    def data(self):
        return NDArray(self._values)

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        if stype == "csr":
            return self
        raise ValueError(f"cannot cast csr to {stype}")

    def __repr__(self):
        return (f"\n<CSRNDArray {self._sshape} "
                f"nnz={int(self._values.shape[0])}>")


# ---------------------------------------------------------- construct ----

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (values, indices) or a dense source
    (reference: python/mxnet/ndarray/sparse.py row_sparse_array)."""
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 2 \
            and not _np.isscalar(arg1[0]):
        values, indices = arg1
        return RowSparseNDArray(values, indices, shape)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    if dtype is not None:
        dense = dense.astype(dtype)
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(dense[nz], nz, dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr) or a dense source
    (reference: python/mxnet/ndarray/sparse.py csr_matrix)."""
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(data, indptr, indices, shape)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    if dtype is not None:
        dense = dense.astype(dtype)
    mask = dense != 0
    indptr = _np.concatenate([[0], _np.cumsum(mask.sum(axis=1))])
    cols = _np.nonzero(mask)[1]
    data = dense[mask]
    return CSRNDArray(data, indptr.astype(_np.int32),
                      cols.astype(_np.int32), dense.shape)


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = dtype or _np.float32
    if stype == "row_sparse":
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(shape[1:]), dtype),
            jnp.zeros((0,), jnp.int32), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype),
                          jnp.zeros((shape[0] + 1,), jnp.int32),
                          jnp.zeros((0,), jnp.int32), shape)
    return NDArray(jnp.zeros(tuple(shape), dtype))


# ------------------------------------------------------------- compute ----

def cast_storage(arr, stype):
    """Reference: src/operator/tensor/cast_storage.cc."""
    if stype == "default":
        return NDArray(arr._data) if not isinstance(arr, NDArray) \
            else NDArray(arr._data)
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise ValueError(stype)


def retain(rsp, row_ids):
    """Keep only the requested rows of a row-sparse array (reference:
    src/operator/tensor/sparse_retain.cc _retain). Rows absent from the
    source come back as zero rows."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError("retain expects a RowSparseNDArray")
    ids = row_ids._data if isinstance(row_ids, NDArray) \
        else jnp.asarray(row_ids, jnp.int32)
    ids = ids.astype(jnp.int32)
    # membership of each source row in row_ids, O(nnz * nids) compare —
    # structural and jittable; vocab-scale dense scatter is avoided
    keep = (rsp._indices[:, None] == ids[None, :]).any(axis=1)
    vals = jnp.where(keep.reshape((-1,) + (1,) * (rsp._values.ndim - 1)),
                     rsp._values, 0)
    return RowSparseNDArray(vals, rsp._indices, rsp._sshape)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot (reference: src/operator/tensor/dot.cc).

    csr @ dense and csr.T @ dense run as gather + segment-sum (one FLOP
    per stored nonzero — genuinely sparse compute); row_sparse operands
    fall back to their dense form (XLA dense dot is the fast path on the
    MXU once density is nontrivial).
    """
    from ..ops.invoke import apply_fn
    if isinstance(lhs, CSRNDArray) and not transpose_b:
        rows = lhs._row_ids()
        cols = lhs._indices
        vals = lhs._values
        n_seg = lhs._sshape[1] if transpose_a else lhs._sshape[0]
        gather = rows if transpose_a else cols
        scatter = cols if transpose_a else rows
        # the CSR structure is a constant of the closure; the dense rhs
        # is a differentiable input, routed through apply_fn so the
        # autograd tape sees the op (grad wrt rhs = csr.T @ dy via the
        # jax.vjp of this same gather/segment-sum program)
        def csr_dot(dense):
            if dense.ndim == 1:           # matrix @ vector
                contrib = vals * dense[gather]
            else:
                contrib = vals[:, None] * dense[gather]
            return jax.ops.segment_sum(contrib, scatter,
                                       num_segments=n_seg)
        rhs_nd = rhs if isinstance(rhs, NDArray) else NDArray(
            jnp.asarray(rhs))
        return apply_fn(csr_dot, [rhs_nd])

    def dense_dot(a, b):
        if transpose_a:
            a = a.T
        if transpose_b:
            b = b.T
        return jnp.dot(a, b)

    a_nd = lhs if isinstance(lhs, NDArray) else NDArray(jnp.asarray(lhs))
    b_nd = rhs if isinstance(rhs, NDArray) else NDArray(jnp.asarray(rhs))
    return apply_fn(dense_dot, [a_nd, b_nd])


def add(lhs, rhs):
    """Sparse-aware add: row_sparse + row_sparse stays row_sparse
    (concatenate index/value lists — duplicate indices are legal and
    densify additively, matching scatter-add semantics)."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        if lhs._sshape != rhs._sshape:
            raise ValueError("shape mismatch")
        return RowSparseNDArray(
            jnp.concatenate([lhs._values, rhs._values]),
            jnp.concatenate([lhs._indices, rhs._indices]), lhs._sshape)
    return NDArray(lhs._data + rhs._data)
