"""Executor: bound symbolic graph.

Reference: python/mxnet/executor.py + src/executor/graph_executor.cc. The
reference's bind pipeline (gradient graph, CSE, fusion, memory planning,
op caching/bulking — graph_executor.cc:1004-1364) is replaced wholesale
by ``jax.jit``: forward is the jitted DAG trace; backward is a jitted
vjp that REMATERIALIZES the forward (recompute-over-store — the TPU
recipe for trading FLOPs for HBM; the reference's analogue was
MXNET_BACKWARD_DO_MIRROR). Aux states (BatchNorm running stats) come
back as extra outputs and are written into the aux arrays after each
training forward.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from . import _rng

__all__ = ["Executor"]


class Executor:
    """Holds bound arrays + compiled forward/backward for a Symbol."""

    def __init__(self, symbol, ctx, args: Dict[str, NDArray],
                 args_grad: Optional[Dict[str, NDArray]], grad_req,
                 aux_states: Dict[str, NDArray]):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.input_names = symbol.list_inputs()
        self.arg_dict = dict(args)
        self.aux_dict = dict(aux_states)
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self.arg_names}
        self.grad_req = grad_req
        self.grad_dict = dict(args_grad) if args_grad else {}
        self.outputs: List[NDArray] = []
        self._jit_fwd = None
        self._jit_bwd = None
        self._last = None  # (rng, arrays) of the last training forward
        self._monitor_callback = None

    # ------------------------------------------------------- array views --
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self.arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self.arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self.aux_names]

    def _build(self):
        if self._jit_fwd is not None:
            return
        sym = self._symbol
        names = self.input_names
        wrt = [n for n in self.arg_names
               if self.grad_req.get(n, "null") != "null"]
        idx = {n: i for i, n in enumerate(names)}
        wrt_idx = [idx[n] for n in wrt]

        def make_fwd(training):
            raw = sym._build_fn(names, collect_aux=True,
                                is_train=training, rng_from_input=True)

            def fwd(rng, *arrays):
                out, aux = raw(rng, *arrays)
                outs = out if isinstance(out, tuple) else (out,)
                return tuple(outs), aux
            return jax.jit(fwd)

        self._jit_fwd = {True: make_fwd(True), False: make_fwd(False)}
        raw_t = sym._build_fn(names, collect_aux=True, is_train=True,
                              rng_from_input=True)

        def bwd(rng, arrays, cots):
            def f(wrt_vals):
                full = list(arrays)
                for i, v in zip(wrt_idx, wrt_vals):
                    full[i] = v
                out, _aux = raw_t(rng, *full)
                outs = out if isinstance(out, tuple) else (out,)
                return tuple(outs)

            _, vjp_fn = jax.vjp(f, tuple(arrays[i] for i in wrt_idx))
            return vjp_fn(tuple(cots))[0]

        self._jit_bwd = jax.jit(bwd)
        self._wrt = wrt

    def forward(self, is_train=False, **kwargs):
        """Run forward (reference: executor.py forward). kwargs update
        bound input arrays by name."""
        for k, v in kwargs.items():
            if k not in self.arg_dict and k not in self.aux_dict:
                raise MXNetError(f"unknown input {k!r}")
            tgt = self.arg_dict.get(k, self.aux_dict.get(k))
            src = v if isinstance(v, NDArray) else NDArray(v)
            tgt._data = jnp.asarray(src._data, dtype=tgt.dtype)
        self._build()
        arrays = []
        for n in self.input_names:
            a = self.arg_dict.get(n, self.aux_dict.get(n))
            if a is None:
                raise MXNetError(f"input {n!r} was not bound")
            arrays.append(a._data)
        rng = _rng.next_key()
        outs, aux = self._jit_fwd[bool(is_train)](rng, *arrays)
        self.outputs = [NDArray(o) for o in outs]
        if is_train:
            self._last = (rng, arrays)
            for n, v in aux.items():
                if n in self.aux_dict:
                    self.aux_dict[n]._data = v
        if self._monitor_callback is not None:
            for name, o in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, o)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """Accumulate gradients into grad arrays. The backward program
        recomputes the forward under jit (rematerialization) using the
        saved rng, so dropout masks match the forward pass."""
        if self._last is None:
            raise MXNetError("call forward(is_train=True) before backward")
        rng, arrays = self._last
        if out_grads is None:
            cots = [jnp.ones_like(o._data) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                    for g in out_grads]
        gwrt = self._jit_bwd(rng, tuple(arrays), tuple(cots))
        for n, g in zip(self._wrt, gwrt):
            req = self.grad_req.get(n, "null")
            if req == "null":
                continue
            buf = self.grad_dict.get(n)
            if buf is None:
                buf = NDArray(jnp.zeros_like(g))
                self.grad_dict[n] = buf
            if req == "add":
                buf._data = buf._data + g
            else:
                buf._data = g

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Return a new executor bound at new shapes (XLA retraces per
        shape, so this is just a rebind; reference: executor.py:reshape)."""
        args = {}
        for n in self.arg_names:
            old = self.arg_dict[n]
            if n in kwargs:
                args[n] = NDArray(jnp.zeros(kwargs[n], old.dtype))
            else:
                args[n] = old
        grads = {n: NDArray(jnp.zeros_like(a._data))
                 for n, a in args.items()
                 if self.grad_req.get(n, "null") != "null"} \
            if self.grad_dict else None
        return Executor(self._symbol, self._ctx, args, grads,
                        self.grad_req, dict(self.aux_dict))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """Load parameter values (reference: executor.py
        copy_params_from)."""
        for name, array in arg_params.items():
            if name in self.arg_dict:
                array.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise ValueError(f"Found name \"{name}\" that is not in "
                                 "the arguments")
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    array.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise ValueError(f"Found name \"{name}\" that is not "
                                     "in the auxiliary states")

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))
