"""Linear-algebra ops (``linalg_*`` / ``la_*`` family).

TPU-native replacement of the reference's LAPACK/cuSOLVER-backed linalg ops
(reference: src/operator/tensor/la_op.cc, src/operator/linalg.h,
c_lapack_api.h). Dense factorizations ride XLA's native TPU implementations
(QR/Cholesky/triangular-solve run on the MXU); there is no LAPACK dispatch
layer to manage.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import _REGISTRY, Operator, alias


def _reg(name, fn, nout=1, differentiable=True):
    _REGISTRY[name] = Operator(name, fn, nout=nout,
                               differentiable=differentiable)


def _gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


def _gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
          beta=1.0, axis=-2):
    return _gemm2(a, b, transpose_a, transpose_b, alpha) + beta * c


_reg("_linalg_gemm2", _gemm2)
_reg("_linalg_gemm", _gemm)
alias("linalg_gemm2", "_linalg_gemm2")
alias("linalg_gemm", "_linalg_gemm")

_reg("_linalg_potrf", lambda a: jnp.linalg.cholesky(a))
alias("linalg_potrf", "_linalg_potrf")


def _potri(a):
    # input is the Cholesky factor L (reference la_op potri contract)
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = lax.linalg.triangular_solve(a, eye, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


_reg("_linalg_potri", _potri)
alias("linalg_potri", "_linalg_potri")


def _trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    out = lax.linalg.triangular_solve(
        a, alpha * b, left_side=not rightside, lower=lower,
        transpose_a=transpose)
    return out


_reg("_linalg_trsm", _trsm)
alias("linalg_trsm", "_linalg_trsm")


def _trmm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b))


_reg("_linalg_trmm", _trmm)
alias("linalg_trmm", "_linalg_trmm")


def _syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


_reg("_linalg_syrk", _syrk)
alias("linalg_syrk", "_linalg_syrk")

_reg("_linalg_syevd", lambda a: jnp.linalg.eigh(a), nout=2)
alias("linalg_syevd", "_linalg_syevd")


def _gelqf(a):
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


_REGISTRY["_linalg_gelqf"] = Operator("_linalg_gelqf", _gelqf, nout=2)
alias("linalg_gelqf", "_linalg_gelqf")

_reg("_linalg_sumlogdiag",
     lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1))
alias("linalg_sumlogdiag", "_linalg_sumlogdiag")


def _extractdiag(a, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


_reg("_linalg_extractdiag", _extractdiag)
alias("linalg_extractdiag", "_linalg_extractdiag")


def _makediag(a, offset=0):
    n = a.shape[-1] + abs(offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    return out.at[..., r, c].set(a)


_reg("_linalg_makediag", _makediag)
alias("linalg_makediag", "_linalg_makediag")

_reg("_linalg_inverse", lambda a: jnp.linalg.inv(a))
alias("linalg_inverse", "_linalg_inverse")
_reg("_linalg_det", lambda a: jnp.linalg.det(a))
alias("linalg_det", "_linalg_det")


def _slogdet(a):
    sign, ld = jnp.linalg.slogdet(a)
    return sign, ld


_REGISTRY["_linalg_slogdet"] = Operator("_linalg_slogdet", _slogdet, nout=2)
alias("linalg_slogdet", "_linalg_slogdet")

_reg("khatri_rao", lambda *mats: _khatri_rao(mats))


def _khatri_rao(mats):
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:])
    return out
