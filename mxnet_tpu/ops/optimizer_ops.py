"""Fused optimizer update ops.

TPU-native replacement of the reference's in-graph optimizer kernels
(reference: src/operator/optimizer_op.cc — sgd_update, sgd_mom_update,
adam_update, …; src/operator/contrib/adamw.cc). The reference fuses each
update into one CUDA kernel and offers multi-tensor (multi_sgd_*) variants
to amortize launches; under XLA a whole optimizer step jitted together is
already one fused program, so each op here is the plain math. The
``mutates`` registration makes the wrapper rebind the weight/state buffers,
preserving the reference's in-place (kWriteInplace) API contract.

All ops apply the reference's common pre-processing: grad = rescale_grad *
grad, optionally clipped to [-clip_gradient, clip_gradient], plus wd.

Dispatch contract (ops/invoke.py): every mutates op here executes as ONE
compiled program (invoke._run_mutates), and the whole-trainer fused apply
(optimizer/fused.py) replays the same impls inside a single jitted,
buffer-donating step. Float kwargs in ``invoke.TRACED_HYPERPARAMS`` (lr,
wd, momentum, rescale_grad) arrive as traced scalars so per-step schedules
never recompile — impls must only use them ARITHMETICALLY. Kwargs an impl
branches on in Python (clip_gradient/clip_weights/lower/upper_bound,
bias_correction) stay static and re-key the compile cache when changed;
an int-valued kwarg (lamb phase1's ``t``) keeps that op on the direct
eager path so it does not bake one program per step.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import _REGISTRY, Operator


def _reg(name, fn, nout, mutates):
    _REGISTRY[name] = Operator(name, fn, nout=nout, differentiable=False,
                               mutates=mutates)


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


_reg("sgd_update", _sgd_update, 1, (0,))


def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


_reg("sgd_mom_update", _sgd_mom_update, 2, (0, 2))


def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


_reg("nag_mom_update", _nag_mom_update, 2, (0, 2))


def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


_reg("mp_sgd_update", _mp_sgd_update, 2, (0, 2))


def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       lazy_update=True):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


_reg("mp_sgd_mom_update", _mp_sgd_mom_update, 3, (0, 2, 3))


def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * m / (jnp.sqrt(v) + epsilon), m, v


_reg("adam_update", _adam_update, 3, (0, 2, 3))


def _adamw_update(weight, grad, mean, var, rescale_grad_arr=None, lr=0.001,
                  beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                  rescale_grad=1.0, clip_gradient=-1.0):
    rs = rescale_grad_arr if rescale_grad_arr is not None else rescale_grad
    g = grad * rs
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return (weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight),
            m, v)


_REGISTRY["_adamw_update"] = Operator(
    "_adamw_update", lambda w, g, m, v, r=None, **kw:
    _adamw_update(w, g, m, v, r, **kw), nout=3, differentiable=False,
    mutates=(0, 2, 3))


def _rmsprop_update(weight, grad, n, lr=0.001, rho=0.9, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = rho * n + (1 - rho) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


_reg("rmsprop_update", _rmsprop_update, 2, (0, 2))


def _rmspropalex_update(weight, grad, n, g_avg, delta, lr=0.001, rho=0.9,
                        momentum=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = rho * n + (1 - rho) * jnp.square(g)
    new_g = rho * g_avg + (1 - rho) * g
    new_delta = (momentum * delta
                 - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon))
    w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_g, new_delta


_reg("rmspropalex_update", _rmspropalex_update, 4, (0, 2, 3, 4))


def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, new_z, new_n


_reg("ftrl_update", _ftrl_update, 3, (0, 2, 3))


def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


_reg("signsgd_update", _signsgd_update, 1, (0,))


def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return w, new_mom


_reg("signum_update", _signum_update, 2, (0, 2))


def _lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                        epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mhat = m / (1 - beta1 ** t)
        vhat = v / (1 - beta2 ** t)
    else:
        mhat, vhat = m, v
    return mhat / (jnp.sqrt(vhat) + epsilon) + wd * weight, m, v


_REGISTRY["lamb_update_phase1"] = Operator(
    "lamb_update_phase1",
    lambda w, g, m, v, **kw: _lamb_update_phase1(w, g, m, v, **kw),
    nout=3, differentiable=False, mutates=())


def _lamb_update_phase2(weight, g, r1, r2, lr=0.01, lower_bound=-1.0,
                        upper_bound=-1.0):
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2,
                      jnp.ones_like(r1))
    if lower_bound is not None and lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    return weight - lr * ratio * g


_reg("lamb_update_phase2", _lamb_update_phase2, 1, (0,))


def _adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_h = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_h) + epsilon), new_h


_reg("_adagrad_update", _adagrad_update, 2, (0, 2))
