"""Paged LoRA delta: the adapter-augmented projection for the flat step.

Multi-LoRA serving (ISSUE 17) keeps the PR 12 contract — everything
request-specific rides the batch as traced data, never as program
structure. An adapter's low-rank factors live in a fixed paged pool
(``serving/adapters/bank.py``): ``a_pages [P, L, 4, d, r]`` and
``b_pages [P, L, 4, r, d]``, where axis 2 indexes the four attention
projections ``(wq, wk, wv, wo)`` and ``r`` is the page rank. A request
using adapter rank ``R`` owns ``ceil(R / r)`` pages (the tail page is
zero-padded — zero factor columns contribute an exactly-zero delta).
Per-row page tables and scales ride the batch like the PR 12 sampling
vectors, so a mixed-adapter batch — including adapter-less rows, whose
table points at the all-zero reserved null page 0 with scale 0 — runs
in ONE fixed-shape program and adapter switch never recompiles.

``paged_lora_delta`` is the single delta expression shared by the flat
step (``model.decode_flat``), the dense oracle (``model.forward``) and
the incremental oracle, so parity between them exercises identical
einsum structure; ``lora_delta`` registers the dense one-adapter form
in the op registry (the paper's one-registry thesis: the same op backs
eager fine-tuning and compiled serving).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

# index of each projection along the factor-pool axis 2
PROJ_Q, PROJ_K, PROJ_V, PROJ_O = 0, 1, 2, 3
NUM_PROJ = 4


def paged_lora_delta(x, a_sel, b_sel, scale):
    """Per-token paged low-rank delta ``scale * (x @ A) @ B``.

    x      [T, d]        activations entering one projection
    a_sel  [T, P, d, r]  per-token gathered A factor pages
    b_sel  [T, P, r, d]  per-token gathered B factor pages
    scale  [T]           per-token LoRA scaling (alpha / rank; 0 = off)

    Pages are rank slices of one factor: summing page contributions
    equals the full-rank product because ``x @ [A1|A2] @ [[B1],[B2]]``
    ``= x@A1@B1 + x@A2@B2``. Null/padded pages are all-zero, so their
    contribution is exactly zero and adapter-less rows return an exact
    zero delta (value-identical to no LoRA at all).
    """
    xa = jnp.einsum("td,tpdr->tpr", x, a_sel)
    delta = jnp.einsum("tpr,tprd->td", xa, b_sel)
    return delta * scale[:, None]


def gather_adapter(a_pages, b_pages, pages_tok, layer, proj):
    """Gather one (layer, projection)'s factor pages for every token.

    a_pages [P_pool, L, 4, d, r], b_pages [P_pool, L, 4, r, d],
    pages_tok [T, P] int32 page ids (0 = null). Returns
    (a_sel [T, P, d, r], b_sel [T, P, r, d]) for ``paged_lora_delta``.
    The gather is traced — page ids are data, so installing, evicting
    or switching adapters never changes program structure.
    """
    return a_pages[pages_tok, layer, proj], b_pages[pages_tok, layer, proj]


@register("lora_delta")
def lora_delta(x, a, b, alpha=1.0):
    """Dense single-adapter LoRA delta ``(alpha / rank) * x @ a @ b``
    (a ``[d, R]``, b ``[R, d]``, x ``[..., d]``): the eager/registry
    form of the serving-side :func:`paged_lora_delta`."""
    rank = a.shape[-1]
    return (x @ a) @ b * (float(alpha) / float(rank))
