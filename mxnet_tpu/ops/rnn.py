"""Fused RNN operator: LSTM / GRU / vanilla RNN via ``lax.scan``.

TPU-native replacement of the reference's fused RNN op
(reference: src/operator/rnn-inl.h:419-481 — cuDNN-backed on GPU, native
CPU kernels otherwise). Design:

- The input projection ``x_t @ Wx^T + bx`` for ALL timesteps is hoisted out
  of the recurrence into one (T*N, I) x (I, G*H) matmul — a single large
  MXU-friendly contraction — so the ``lax.scan`` body carries only the
  unavoidable sequential part ``h_{t-1} @ Wh^T``.
- Multi-layer and bidirectional composition is a Python loop at trace time
  (static ``num_layers``/``bidirectional``), producing one fused XLA
  program, with inter-layer dropout like cuDNN (vertical connections only).
- Gate order matches the reference/cuDNN convention so packed parameter
  vectors interchange: LSTM [i, f, g, o]; GRU [r, z, n].

The registered ``RNN`` op takes the reference's flat parameter vector
(layer-major, direction-minor: all [Wx, Wh] blocks first, then all
[bx, bh] blocks — src/operator/rnn-inl.h GetRnnParamSize) and unpacks it
at trace time (pure reshape/slice: free under XLA).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}

__all__ = ["rnn_param_size", "rnn_cell_step", "rnn_layer_scan"]


def rnn_param_size(input_size, state_size, num_layers, mode,
                   bidirectional=False, projection_size=None):
    """Total flat parameter count (reference: rnn-inl.h GetRnnParamSize)."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        size += d * (g * state_size * (in_sz + state_size)      # Wx, Wh
                     + 2 * g * state_size)                      # bx, bh
    return size


def _unpack_params(params, input_size, state_size, num_layers, mode,
                   bidirectional):
    """Flat parameter vector -> per-layer/direction weight dicts."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    weights, biases = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * d
        lw = []
        for _ in range(d):
            wx = params[off:off + g * h * in_sz].reshape(g * h, in_sz)
            off += g * h * in_sz
            wh = params[off:off + g * h * h].reshape(g * h, h)
            off += g * h * h
            lw.append({"wx": wx, "wh": wh})
        weights.append(lw)
    for layer in range(num_layers):
        lb = []
        for _ in range(d):
            bx = params[off:off + g * h]
            off += g * h
            bh = params[off:off + g * h]
            off += g * h
            lb.append({"bx": bx, "bh": bh})
        biases.append(lb)
    for layer in range(num_layers):
        for dd in range(d):
            weights[layer][dd].update(biases[layer][dd])
    return weights


def rnn_cell_step(mode, xproj, h, c, wh, bh):
    """One recurrence step for lstm/rnn_relu/rnn_tanh (GRU needs the
    reset-gated candidate form — see _gru_layer_scan). ``xproj`` is the
    precomputed input projection (N, G*H); returns (out, new_h, new_c)."""
    gates = xproj + h @ wh.T + bh
    if mode == "lstm":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        new_c = f * c + i * g
        new_h = o * jnp.tanh(new_c)
        return new_h, new_h, new_c
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu
    new_h = act(gates)
    return new_h, new_h, c


def rnn_layer_scan(mode, x, h0, c0, w, reverse=False):
    """Scan one direction of one layer over time (non-GRU modes).

    x: (T, N, I); h0/c0: (N, H); w: dict(wx, wh, bx, bh).
    Returns (out (T, N, H), hT, cT).
    """
    T, N, _ = x.shape
    # hoisted input projection: one big matmul over all timesteps
    xproj = (x.reshape(T * N, -1) @ w["wx"].T + w["bx"]).reshape(T, N, -1)
    if reverse:
        xproj = jnp.flip(xproj, axis=0)

    def step(carry, xp):
        h, c = carry
        out, nh, nc = rnn_cell_step(mode, xp, h, c, w["wh"], w["bh"])
        return (nh, nc), out

    (hT, cT), out = lax.scan(step, (h0, c0), xproj)
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, hT, cT


def _gru_layer_scan(x, h0, w, reverse=False):
    """GRU direction scan with the reset-gated candidate recurrence."""
    T, N, _ = x.shape
    xproj = (x.reshape(T * N, -1) @ w["wx"].T + w["bx"]).reshape(T, N, -1)
    if reverse:
        xproj = jnp.flip(xproj, axis=0)

    def step(h, xp):
        xr, xz, xn = jnp.split(xp, 3, axis=-1)
        hr, hz, hn = jnp.split(h @ w["wh"].T + w["bh"], 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        nh = (1 - z) * n + z * h
        return nh, nh

    hT, out = lax.scan(step, h0, xproj)
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, hT


def rnn_forward(data, params_flat, h0, c0, mode, state_size, num_layers,
                bidirectional=False, p=0.0, training=False, rng=None):
    """Full fused-RNN forward. data: (T, N, I); h0: (L*D, N, H).

    Returns (out (T, N, D*H), hT (L*D, N, H), cT or None).
    """
    d = 2 if bidirectional else 1
    w = _unpack_params(params_flat, data.shape[-1], state_size,
                       num_layers, mode, bidirectional)
    x = data
    hTs, cTs = [], []
    for layer in range(num_layers):
        outs = []
        for di in range(d):
            h_init = h0[layer * d + di]
            c_init = (c0[layer * d + di] if c0 is not None
                      else jnp.zeros_like(h_init))
            if mode == "gru":
                out, hT = _gru_layer_scan(x, h_init, w[layer][di],
                                          reverse=(di == 1))
                cT = c_init
            else:
                out, hT, cT = rnn_layer_scan(mode, x, h_init, c_init,
                                             w[layer][di],
                                             reverse=(di == 1))
            outs.append(out)
            hTs.append(hT)
            cTs.append(cT)
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and training and layer < num_layers - 1 and rng is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(rng, layer), 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0.0)
    hT = jnp.stack(hTs)
    cT = jnp.stack(cTs) if mode == "lstm" else None
    return x, hT, cT


@register("RNN", nout=3, needs_rng=True, needs_train=True)
def _rnn_op(data, parameters, state, state_cell=None, *, state_size,
            num_layers, mode="lstm", bidirectional=False, p=0.0,
            state_outputs=True, projection_size=None, rng=None,
            _training=False):
    """Fused RNN op (reference: src/operator/rnn-inl.h:419; op docs
    src/operator/rnn.cc). data is TNC; states are (L*D, N, H)."""
    if projection_size is not None:
        raise NotImplementedError("projection_size is not supported")
    out, hT, cT = rnn_forward(
        data, parameters, state, state_cell, mode, state_size, num_layers,
        bidirectional=bidirectional, p=p, training=_training, rng=rng)
    if cT is None:
        cT = jnp.zeros_like(hT)
    return out, hT, cT


alias("rnn", "RNN")
