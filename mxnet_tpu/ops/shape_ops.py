"""Shape manipulation and indexing ops.

TPU-native replacement of the reference's matrix-manipulation and indexing
families (reference: src/operator/tensor/matrix_op.cc — Reshape/Transpose/
slice/Concat/stack/tile/repeat/pad/depth_to_space…, indexing_op.cc —
take/pick/gather_nd/scatter_nd/one_hot, ordering_op.cc — topk/sort/argsort).
Static shapes are computed in Python at trace time (the analogue of the
reference's FInferShape functions), so everything stays jit-compatible.

Reference reshape keyword codes are preserved (matrix_op-inl.h
ReshapeParam): 0 = copy input dim, -1 = infer, -2 = copy all remaining,
-3 = merge next two dims, -4 = split next dim by the following two values.
"""
from __future__ import annotations

import numpy as _np
import jax.numpy as jnp
from jax import lax

from ..base import dtype_np
from .registry import _REGISTRY, Operator, alias


def _reg(name, fn, differentiable=True, nout=1, variadic=False):
    _REGISTRY[name] = Operator(name, fn, nout=nout,
                               differentiable=differentiable,
                               variadic=variadic)


def infer_reshape(src_shape, target):
    """Resolve a reference-style reshape spec against a concrete shape."""
    src = list(src_shape)
    out = []
    i = 0  # cursor into src dims
    t = list(target)
    k = 0
    while k < len(t):
        d = t[k]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            a, b = t[k + 1], t[k + 2]
            sz = src[i]
            if a == -1:
                a = sz // b
            if b == -1:
                b = sz // a
            out.extend([a, b]); i += 1; k += 2
        else:
            out.append(d)
            if i < len(src):
                i += 1
        k += 1
    if out.count(-1):
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src_shape:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


def _reshape(x, shape=None, reverse=False):
    return jnp.reshape(x, infer_reshape(x.shape, shape))


_reg("reshape", _reshape)
alias("Reshape", "reshape")
_reg("reshape_like", lambda x, y: jnp.reshape(x, y.shape))
_reg("transpose", lambda x, axes=None: jnp.transpose(x, axes or None))
_reg("swapaxes", lambda x, dim1=0, dim2=0: jnp.swapaxes(x, dim1, dim2))
alias("SwapAxis", "swapaxes")
_reg("flatten", lambda x: jnp.reshape(x, (x.shape[0], -1)))
alias("Flatten", "flatten")
_reg("expand_dims", lambda x, axis: jnp.expand_dims(x, axis))


def _squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    return jnp.squeeze(x, axis)


_reg("squeeze", _squeeze)


def _broadcast_to(x, shape):
    # reference semantics: 0 in target keeps the source dim
    tgt = tuple(s if t == 0 else t for s, t in zip(x.shape, shape)) \
        if len(shape) == x.ndim else tuple(shape)
    return jnp.broadcast_to(x, tgt)


_reg("broadcast_to", _broadcast_to)
_reg("broadcast_like", lambda x, y: jnp.broadcast_to(x, y.shape))


def _broadcast_axis(x, axis=(), size=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


_reg("broadcast_axis", _broadcast_axis)
alias("broadcast_axes", "broadcast_axis")


def _tile(x, reps):
    return jnp.tile(x, reps)


_reg("tile", _tile)
_reg("repeat", lambda x, repeats, axis=None: jnp.repeat(x, repeats, axis=axis))


def _flip(x, axis):
    return jnp.flip(x, axis)


_reg("flip", _flip)
alias("reverse", "flip")


def _pad(x, mode="constant", pad_width=(), constant_value=0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(x.ndim)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(x, pw, mode=jmode)


_reg("pad", _pad)
alias("Pad", "pad")

_reg("concat", lambda xs, dim=1, num_args=None: jnp.concatenate(xs, axis=dim),
     variadic=True)
alias("Concat", "concat")
_reg("stack", lambda xs, axis=0, num_args=None: jnp.stack(xs, axis=axis),
     variadic=True)


def _split(x, num_outputs=None, axis=1, squeeze_axis=False, sections=None):
    n = num_outputs or sections
    parts = jnp.split(x, n, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


_REGISTRY["split"] = Operator("split", _split, nout=-1)
alias("SliceChannel", "split")


def _slice(x, begin, end, step=None):
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(slice(b, e, s))
    return x[tuple(idx)]


_reg("slice", _slice)


def _slice_axis(x, axis, begin, end):
    idx = [slice(None)] * x.ndim
    if end is None:
        end = x.shape[axis]
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


_reg("slice_axis", _slice_axis)


def _slice_like(x, y, axes=()):
    axes = tuple(axes) if axes else tuple(range(min(x.ndim, y.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, y.shape[a])
    return x[tuple(idx)]


_reg("slice_like", _slice_like)

_reg("clip", lambda x, a_min=None, a_max=None: jnp.clip(x, a_min, a_max))


def _take(x, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, x.shape[axis])
    else:
        idx = jnp.clip(idx, 0, x.shape[axis] - 1)
    return jnp.take(x, idx, axis=axis)


_reg("take", _take)


def _batch_take(x, indices):
    return x[jnp.arange(x.shape[0]), indices.astype(jnp.int32)]


_reg("batch_take", _batch_take)


def _pick(x, index, axis=-1, keepdims=False, mode="clip"):
    idx = index.astype(jnp.int32)
    ax = axis % x.ndim
    idx = jnp.clip(idx, 0, x.shape[ax] - 1)
    idxe = jnp.expand_dims(idx, ax)
    out = jnp.take_along_axis(x, idxe, axis=ax)
    return out if keepdims else jnp.squeeze(out, ax)


_reg("pick", _pick)


def _gather_nd(x, indices):
    ind = indices.astype(jnp.int32)
    return x[tuple(ind[i] for i in range(ind.shape[0]))]


_reg("gather_nd", _gather_nd)


def _scatter_nd(data, indices, shape):
    ind = indices.astype(jnp.int32)
    out = jnp.zeros(tuple(shape), data.dtype)
    return out.at[tuple(ind[i] for i in range(ind.shape[0]))].set(data)


_reg("scatter_nd", _scatter_nd)


def _one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    d = dtype_np(dtype)
    oh = jnp.equal(jnp.expand_dims(indices.astype(jnp.int32), -1),
                   jnp.arange(depth))
    return jnp.where(oh, _np.array(on_value, d), _np.array(off_value, d))


_reg("one_hot", _one_hot, differentiable=False)


def _sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


_reg("sort", _sort)


def _argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype_np(dtype))


_reg("argsort", _argsort, differentiable=False)


def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    ax = axis % x.ndim
    xm = jnp.moveaxis(x, ax, -1)
    if is_ascend:
        vals, idx = lax.top_k(-xm, k)
        vals = -vals
    else:
        vals, idx = lax.top_k(xm, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax).astype(dtype_np(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        raise NotImplementedError("topk ret_typ='mask'")


_REGISTRY["topk"] = Operator("topk", _topk, nout=-1, differentiable=False)

_reg("shape_array", lambda x: jnp.array(x.shape, jnp.int32),
     differentiable=False)
_reg("size_array", lambda x: jnp.array([x.size], jnp.int32),
     differentiable=False)
_reg("cast", lambda x, dtype: x.astype(dtype_np(dtype)))
alias("Cast", "cast")


def _diag(x, k=0):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


_reg("diag", _diag)


def _depth_to_space(x, block_size):
    b, c, h, w = x.shape
    bs = block_size
    y = x.reshape(b, bs, bs, c // (bs * bs), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return y.reshape(b, c // (bs * bs), h * bs, w * bs)


def _space_to_depth(x, block_size):
    b, c, h, w = x.shape
    bs = block_size
    y = x.reshape(b, c, h // bs, bs, w // bs, bs)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(b, c * bs * bs, h // bs, w // bs)


_reg("depth_to_space", _depth_to_space)
_reg("space_to_depth", _space_to_depth)


# --- sequence ops (reference: src/operator/sequence_mask.cc, sequence_last.cc,
#     sequence_reverse.cc; layout (seq_len, batch, ...)) ---------------------

def _seq_steps(x):
    return jnp.arange(x.shape[0])[:, None]


def _sequence_mask(x, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return x
    if axis == 1:
        x = jnp.swapaxes(x, 0, 1)
    mask = _seq_steps(x) < sequence_length[None, :]
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    out = jnp.where(mask, x, jnp.asarray(value, x.dtype))
    return jnp.swapaxes(out, 0, 1) if axis == 1 else out


def _sequence_last(x, sequence_length=None, use_sequence_length=False, axis=0):
    if axis == 1:
        x = jnp.swapaxes(x, 0, 1)
    if not use_sequence_length or sequence_length is None:
        return x[-1]
    idx = (sequence_length.astype(jnp.int32) - 1)
    return jnp.take_along_axis(
        x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0)[0]


def _sequence_reverse(x, sequence_length=None, use_sequence_length=False,
                      axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(x, axis=0)
    T = x.shape[0]
    steps = jnp.arange(T)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(steps < lens, lens - 1 - steps, steps)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=0)


_reg("SequenceMask", _sequence_mask)
alias("sequence_mask", "SequenceMask")
_reg("SequenceLast", _sequence_last)
alias("sequence_last", "SequenceLast")
_reg("SequenceReverse", _sequence_reverse)
alias("sequence_reverse", "SequenceReverse")


# ------------------------------------------------------- creation ops ------
# (registered so the Symbol API can carry creation nodes in its DAG;
# reference: src/operator/tensor/init_op.cc _zeros/_ones/_arange/_eye)

def _creation_reg(name, fn):
    _REGISTRY[name] = Operator(name, fn, differentiable=False)


def _zeros_impl(shape=(), dtype="float32", ctx=None):
    return jnp.zeros(tuple(shape), _np.dtype(dtype))


def _ones_impl(shape=(), dtype="float32", ctx=None):
    return jnp.ones(tuple(shape), _np.dtype(dtype))


def _full_impl(shape=(), value=0.0, dtype="float32", ctx=None):
    return jnp.full(tuple(shape), value, _np.dtype(dtype))


def _arange_impl(start=0.0, stop=None, step=1.0, repeat=1, ctx=None,
                 dtype="float32"):
    out = jnp.arange(start, stop, step, _np.dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


def _eye_impl(N=0, M=0, k=0, dtype="float32", ctx=None):
    return jnp.eye(int(N), int(M) or None, int(k), dtype=_np.dtype(dtype))


_creation_reg("_zeros", _zeros_impl)
_creation_reg("_ones", _ones_impl)
_creation_reg("_full", _full_impl)
_creation_reg("_arange", _arange_impl)
_creation_reg("_eye", _eye_impl)
