"""Op registry + families. Importing this package registers all ops.

TPU-native analogue of the reference's src/operator/ tree: each submodule
mirrors one reference op family (see the per-file docstrings for the
file:line provenance map).
"""
from .registry import Operator, register, get, list_ops, alias  # noqa: F401
from . import elemwise      # noqa: F401
from . import reduce        # noqa: F401
from . import shape_ops     # noqa: F401
from . import nn            # noqa: F401
from . import rnn           # noqa: F401
from . import flash_attention  # noqa: F401
from . import ragged_attention  # noqa: F401
from . import contrib_det   # noqa: F401
from . import contrib_det2  # noqa: F401
from . import extra         # noqa: F401
from . import linalg        # noqa: F401
from . import random_ops    # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import quantization  # noqa: F401
from . import lora          # noqa: F401
from .invoke import apply_op, apply_fn  # noqa: F401
# mx.operator registers the 'Custom' op (user Python ops over
# jax.pure_callback); import it before the nd namespace is generated
from .. import operator as _custom_operator  # noqa: F401,E402
