"""Detection op tail: RPN proposals, position-sensitive / deformable /
rotated ROI ops, Mask R-CNN targets, Hawkes log-likelihood.

Reference provenance per op:
- _contrib_Proposal / _contrib_MultiProposal:
  src/operator/contrib/proposal.cc, multi_proposal.cc (RPN: anchor
  grid + bbox-delta decode + clip + min-size filter + top-K + NMS).
- _contrib_PSROIPooling: src/operator/contrib/psroi_pooling.cc (R-FCN
  position-sensitive average pooling).
- _contrib_DeformableConvolution / _contrib_ModulatedDeformable...:
  src/operator/contrib/deformable_convolution.cc,
  modulated_deformable_convolution.cc (DCN v1/v2: bilinear sampling at
  offset tap locations; v2 adds a per-tap mask).
- _contrib_DeformablePSROIPooling:
  src/operator/contrib/deformable_psroi_pooling.cc.
- _contrib_RROIAlign: src/operator/contrib/rroi_align.cc (rotated ROIs
  [batch, cx, cy, w, h, theta_degrees]).
- _contrib_mrcnn_mask_target: src/operator/contrib/mrcnn_mask_target.cc.
- _contrib_hawkesll: src/operator/contrib/hawkes_ll.cc (marked Hawkes
  process log-likelihood; lax.scan over the event sequence replaces the
  reference's per-sample CUDA loop).

TPU-first notes: everything is static-shape (fixed top-K / padded
outputs, masked NMS via fori_loop) so the whole family jits; bilinear
gathers give gradients to data/offsets for free via jax.vjp where the
reference hand-writes backward kernels.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import _REGISTRY, Operator


def _reg(name, fn, **kw):
    _REGISTRY[name] = Operator(name, fn, **kw)


# ----------------------------------------------------------- proposals ----

def _gen_base_anchors(stride, scales, ratios):
    """reference: proposal.cc GenerateAnchors — base box
    [0, 0, stride-1, stride-1], ratio then scale enumeration."""
    base = _np.array([0, 0, stride - 1, stride - 1], _np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    for r in ratios:
        size = w * h
        ws = _np.round(_np.sqrt(size / r))
        hs = _np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return _np.asarray(anchors, _np.float32)          # (A, 4)


def _proposal_single(scores, deltas, im_info, anchors, stride,
                     pre_nms, post_nms, thresh, min_size, iou_loss):
    """scores (A,H,W) fg, deltas (4A,H,W), im_info (3,)=[h,w,scale]."""
    a, h, w = scores.shape
    shift_x = jnp.arange(w) * stride
    shift_y = jnp.arange(h) * stride
    sx, sy = jnp.meshgrid(shift_x, shift_y, indexing="xy")
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).astype(jnp.float32)
    anc = anchors[None, None] + shifts[:, :, None, :]   # (H, W, A, 4)
    anc = anc.reshape(-1, 4)
    dts = deltas.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
    scr = scores.transpose(1, 2, 0).reshape(-1)

    aw = anc[:, 2] - anc[:, 0] + 1
    ah = anc[:, 3] - anc[:, 1] + 1
    cx = anc[:, 0] + 0.5 * (aw - 1)
    cy = anc[:, 1] + 0.5 * (ah - 1)
    if iou_loss:
        x1 = anc[:, 0] + dts[:, 0]
        y1 = anc[:, 1] + dts[:, 1]
        x2 = anc[:, 2] + dts[:, 2]
        y2 = anc[:, 3] + dts[:, 3]
    else:
        pcx = dts[:, 0] * aw + cx
        pcy = dts[:, 1] * ah + cy
        pw = jnp.exp(jnp.clip(dts[:, 2], -10, 10)) * aw
        phh = jnp.exp(jnp.clip(dts[:, 3], -10, 10)) * ah
        x1 = pcx - 0.5 * (pw - 1)
        y1 = pcy - 0.5 * (phh - 1)
        x2 = pcx + 0.5 * (pw - 1)
        y2 = pcy + 0.5 * (phh - 1)
    imh, imw = im_info[0], im_info[1]
    x1 = jnp.clip(x1, 0, imw - 1)
    y1 = jnp.clip(y1, 0, imh - 1)
    x2 = jnp.clip(x2, 0, imw - 1)
    y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=1)

    ms = min_size * im_info[2]
    keep = ((x2 - x1 + 1) >= ms) & ((y2 - y1 + 1) >= ms)
    scr = jnp.where(keep, scr, -jnp.inf)

    k = min(pre_nms, scr.shape[0])
    top_scr, top_idx = lax.top_k(scr, k)
    top_boxes = boxes[top_idx]

    # masked greedy NMS over the pre-NMS top-K (score-descending order)
    def iou(b, ref):
        ix1 = jnp.maximum(b[:, 0], ref[0])
        iy1 = jnp.maximum(b[:, 1], ref[1])
        ix2 = jnp.minimum(b[:, 2], ref[2])
        iy2 = jnp.minimum(b[:, 3], ref[3])
        iw = jnp.maximum(ix2 - ix1 + 1, 0)
        ih = jnp.maximum(iy2 - iy1 + 1, 0)
        inter = iw * ih
        area = lambda bb: (bb[..., 2] - bb[..., 0] + 1) * \
            (bb[..., 3] - bb[..., 1] + 1)           # noqa: E731
        return inter / (area(b) + area(ref) - inter)

    def body(i, keep):
        alive = keep[i] & jnp.isfinite(top_scr[i])
        sup = (iou(top_boxes, top_boxes[i]) > thresh) & \
            (jnp.arange(k) > i)
        return jnp.where(alive, keep & ~sup, keep)

    keep = lax.fori_loop(0, k, body, jnp.ones(k, bool))
    keep = keep & jnp.isfinite(top_scr)
    # stable-compact the kept boxes to the front, pad by repeating box 0
    order = jnp.argsort(~keep, stable=True)[:post_nms]
    sel = jnp.where(keep[order][:, None], top_boxes[order],
                    top_boxes[order][0:1])
    sel_scores = jnp.where(keep[order], top_scr[order], top_scr[order][0])
    return sel, sel_scores


def _proposal(cls_prob, bbox_pred, im_info, scales=(4, 8, 16, 32),
              ratios=(0.5, 1, 2), feature_stride=16,
              rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
              threshold=0.7, rpn_min_size=16, output_score=False,
              iou_loss=False):
    anchors = jnp.asarray(_gen_base_anchors(feature_stride, scales,
                                            ratios))
    a = anchors.shape[0]
    boxes, scores = _proposal_single(
        cls_prob[0, a:], bbox_pred[0], im_info[0], anchors,
        feature_stride, int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n),
        threshold, float(rpn_min_size), iou_loss)
    rois = jnp.concatenate([jnp.zeros((boxes.shape[0], 1),
                                      boxes.dtype), boxes], axis=1)
    if output_score:
        return rois, scores[:, None]
    return rois


_reg("_contrib_Proposal", _proposal, nout=2)


def _multi_proposal(cls_prob, bbox_pred, im_info, scales=(4, 8, 16, 32),
                    ratios=(0.5, 1, 2), feature_stride=16,
                    rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
                    threshold=0.7, rpn_min_size=16, output_score=False,
                    iou_loss=False):
    """reference: multi_proposal.cc — batched Proposal; output
    (N*post_nms, 5) with the batch index in column 0."""
    anchors = jnp.asarray(_gen_base_anchors(feature_stride, scales,
                                            ratios))
    a = anchors.shape[0]

    def one(scores, deltas, info):
        return _proposal_single(
            scores[a:], deltas, info, anchors, feature_stride,
            int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n), threshold,
            float(rpn_min_size), iou_loss)

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    n, p = boxes.shape[:2]
    bidx = jnp.repeat(jnp.arange(n, dtype=boxes.dtype), p)
    rois = jnp.concatenate([bidx[:, None], boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


_reg("_contrib_MultiProposal", _multi_proposal, nout=2)


# --------------------------------------------------------- psroi pooling --

def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                   pooled_size=7, group_size=0):
    """reference: psroi_pooling.cc — bin (i,j) of output channel c
    average-pools channel c*g*g + i*g + j over the bin region."""
    g = int(group_size) if group_size else int(pooled_size)
    p = int(pooled_size)
    n, c, hh, ww = data.shape

    ys = jnp.arange(hh, dtype=jnp.float32)
    xs = jnp.arange(ww, dtype=jnp.float32)

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / p, rw / p
        img = data[bidx]                                   # (C, H, W)

        iy = jnp.arange(p, dtype=jnp.float32)
        ix = jnp.arange(p, dtype=jnp.float32)
        ys1 = jnp.floor(y1 + iy * bh)
        ys2 = jnp.ceil(y1 + (iy + 1) * bh)
        xs1 = jnp.floor(x1 + ix * bw)
        xs2 = jnp.ceil(x1 + (ix + 1) * bw)
        # (p, H) / (p, W) membership masks
        my = (ys[None, :] >= ys1[:, None]) & (ys[None, :] < ys2[:, None])
        mxm = (xs[None, :] >= xs1[:, None]) & (xs[None, :] < xs2[:, None])
        # channel map: out channel c, bin (i, j) <- c*g*g + gi*g + gj
        gi = (iy * g // p).astype(jnp.int32)
        gj = (ix * g // p).astype(jnp.int32)
        cidx = (jnp.arange(output_dim)[:, None, None] * g * g
                + gi[None, :, None] * g + gj[None, None, :])  # (od,p,p)
        chans = img[cidx.reshape(-1)]                   # (od*p*p, H, W)
        chans = chans.reshape(output_dim, p, p, hh, ww)
        mask = (my[:, None, :, None] * mxm[None, :, None, :])  # (p,p,H,W)
        s = jnp.einsum("opqhw,pqhw->opq", chans, mask.astype(data.dtype))
        cnt = jnp.maximum(mask.sum(axis=(2, 3)), 1.0)
        return s / cnt[None]

    return jax.vmap(one)(rois).astype(data.dtype)


_reg("_contrib_PSROIPooling", _psroi_pooling)


# ----------------------------------------------------- deformable convs ---

def _bilinear_nchw(img, y, x):
    """img (C, H, W); y/x arbitrary same-shaped float grids; zero
    outside (the DCN convention)."""
    c, h, w = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0
    out = 0.0
    for dy, wgt_y in ((0, 1 - wy), (1, wy)):
        for dx, wgt_x in ((0, 1 - wx), (1, wx)):
            yy = y0 + dy
            xx = x0 + dx
            inside = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            val = img[:, yi, xi]
            out = out + (wgt_y * wgt_x * inside)[None] * val
    return out                                            # (C, ...)


def _deformable_conv_core(data, offset, weight, bias, mask, kernel,
                          stride, pad, dilate, num_deformable_group,
                          num_group):
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    n, c, h, w = data.shape
    o = weight.shape[0]
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = num_deformable_group
    cg = c // dg

    oy = jnp.arange(ho) * sh - ph
    ox = jnp.arange(wo) * sw - pw

    def one(img, off, msk):
        # off (2*dg*kh*kw, Ho, Wo); sampled (C, kh*kw, Ho, Wo)
        off = off.reshape(dg, kh * kw, 2, ho, wo)
        cols = []
        for t in range(kh * kw):
            ky, kx = divmod(t, kw)
            base_y = oy[:, None] + ky * dh + off[:, t, 0]   # (dg, Ho, Wo)
            base_x = ox[None, :] + kx * dw + off[:, t, 1]
            per_g = []
            for gi in range(dg):
                sub = img[gi * cg:(gi + 1) * cg]
                samp = _bilinear_nchw(sub, base_y[gi], base_x[gi])
                per_g.append(samp)                          # (cg, Ho, Wo)
            s = jnp.concatenate(per_g, axis=0)              # (C, Ho, Wo)
            if msk is not None:
                m = msk.reshape(dg, kh * kw, ho, wo)[:, t]
                s = s.reshape(dg, cg, ho, wo) * m[:, None]
                s = s.reshape(c, ho, wo)
            cols.append(s)
        col = jnp.stack(cols, axis=1)             # (C, kh*kw, Ho, Wo)
        wmat = weight.reshape(o, -1)              # (O, C/g*kh*kw)
        if num_group == 1:
            out = jnp.einsum("ok,khw->ohw",
                             wmat, col.reshape(c * kh * kw, ho, wo))
        else:
            og = o // num_group
            cgr = c // num_group
            col_g = col.reshape(num_group, cgr * kh * kw, ho, wo)
            w_g = weight.reshape(num_group, og, cgr * kh * kw)
            out = jnp.einsum("gok,gkhw->gohw", w_g, col_g)\
                .reshape(o, ho, wo)
        return out

    out = jax.vmap(one)(data, offset, mask)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _deformable_convolution(*args, kernel=(3, 3), stride=(1, 1),
                            pad=(0, 0), dilate=(1, 1), num_filter=0,
                            num_group=1, num_deformable_group=1,
                            no_bias=False, workspace=None, layout=None):
    data, offset, weight = args[0], args[1], args[2]
    bias = args[3] if (not no_bias and len(args) > 3) else None
    return _deformable_conv_core(
        data, offset, weight, bias, None, tuple(kernel), tuple(stride),
        tuple(pad), tuple(dilate), int(num_deformable_group),
        int(num_group))


_reg("_contrib_DeformableConvolution", _deformable_convolution)


def _modulated_deformable_convolution(*args, kernel=(3, 3), stride=(1, 1),
                                      pad=(0, 0), dilate=(1, 1),
                                      num_filter=0, num_group=1,
                                      num_deformable_group=1,
                                      no_bias=False, workspace=None,
                                      layout=None, im2col_step=None):
    data, offset, mask, weight = args[0], args[1], args[2], args[3]
    bias = args[4] if (not no_bias and len(args) > 4) else None
    return _deformable_conv_core(
        data, offset, weight, bias, mask, tuple(kernel), tuple(stride),
        tuple(pad), tuple(dilate), int(num_deformable_group),
        int(num_group))


_reg("_contrib_ModulatedDeformableConvolution",
     _modulated_deformable_convolution)


def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                              output_dim=1, group_size=1, pooled_size=7,
                              part_size=0, sample_per_part=1,
                              trans_std=0.0, no_trans=False):
    """reference: deformable_psroi_pooling.cc — PSROIPooling whose bins
    are shifted by learned normalized offsets; bins sample
    sample_per_part^2 bilinear points."""
    p = int(pooled_size)
    g = int(group_size)
    sp = int(sample_per_part)
    n, c, hh, ww = data.shape

    def one(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / p, rw / p
        img = data[bidx]

        iy = jnp.arange(p, dtype=jnp.float32)
        # per-bin offsets, normalized by roi size (reference trans_std)
        if no_trans or tr is None:
            off_y = jnp.zeros((p, p))
            off_x = jnp.zeros((p, p))
        else:
            pt = int(part_size) if part_size else p
            bin_p = jnp.clip((iy * pt // p).astype(jnp.int32), 0, pt - 1)
            off_y = tr[0, bin_p[:, None], bin_p[None, :]] * trans_std * rh
            off_x = tr[1, bin_p[:, None], bin_p[None, :]] * trans_std * rw
        gi = (iy * g // p).astype(jnp.int32)
        cidx = (jnp.arange(output_dim)[:, None, None] * g * g
                + gi[None, :, None] * g + gi[None, None, :])
        # sample an sp x sp grid per bin at the offset location
        by = y1 + iy[:, None] * bh                         # (p,1)
        bx = x1 + iy[None, :] * bw                         # (1,p)
        sy = (jnp.arange(sp) + 0.5) * (bh / sp)
        sx = (jnp.arange(sp) + 0.5) * (bw / sp)
        yy = by[:, :, None, None] + sy[None, None, :, None] + \
            off_y[:, :, None, None]
        xx = bx[:, :, None, None] + sx[None, None, None, :] + \
            off_x[:, :, None, None]
        yy, xx = jnp.broadcast_arrays(yy, xx)      # (p, p, sp, sp)
        samples = _bilinear_nchw(img, yy.reshape(-1), xx.reshape(-1))
        samples = samples.reshape(c, p, p, sp, sp).mean(axis=(3, 4))
        out = samples[cidx.reshape(-1)].reshape(output_dim, p, p,
                                                p, p)
        out = out[:, jnp.arange(p)[:, None], jnp.arange(p)[None, :],
                  jnp.arange(p)[:, None], jnp.arange(p)[None, :]]
        return out

    if trans is None or no_trans:
        trs = jnp.zeros((rois.shape[0], 2, 1, 1), data.dtype)
    else:
        trs = trans
    return jax.vmap(one)(rois, trs).astype(data.dtype)


_reg("_contrib_DeformablePSROIPooling", _deformable_psroi_pooling)


# ------------------------------------------------------------ rroi align --

def _rroi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
                sampling_ratio=-1):
    """reference: rroi_align.cc — rois (R, 6):
    [batch, cx, cy, w, h, theta_degrees]; bilinear samples on the
    rotated grid, averaged per bin."""
    ph, pw = (pooled_size if hasattr(pooled_size, "__len__")
              else (pooled_size, pooled_size))
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        rw = jnp.maximum(roi[3] * spatial_scale, 1.0)
        rh = jnp.maximum(roi[4] * spatial_scale, 1.0)
        theta = roi[5] * _np.pi / 180.0
        cos_t = jnp.cos(theta)
        sin_t = jnp.sin(theta)
        # local grid in the roi frame, centered
        gy = (jnp.arange(ph * sr) + 0.5) / (ph * sr) - 0.5   # [-.5,.5)
        gx = (jnp.arange(pw * sr) + 0.5) / (pw * sr) - 0.5
        ly, lx = jnp.meshgrid(gy * rh, gx * rw, indexing="ij")
        # rotate and translate into image coords
        ix = cx + lx * cos_t - ly * sin_t
        iy = cy + lx * sin_t + ly * cos_t
        img = data[bidx]
        samples = _bilinear_nchw(img, iy.ravel(), ix.ravel())
        c = data.shape[1]
        samples = samples.reshape(c, ph, sr, pw, sr)
        return samples.mean(axis=(2, 4))

    return jax.vmap(one)(rois).astype(data.dtype)


_reg("_contrib_RROIAlign", _rroi_align)


# -------------------------------------------------------- mrcnn targets --

def _mrcnn_mask_target(rois, gt_masks, matches, cls_targets,
                       num_rois=0, num_classes=0, mask_size=(28, 28),
                       sample_ratio=2, aligned=False):
    """reference: mrcnn_mask_target.cc — crop each roi's matched GT
    mask to (mask_size, mask_size) via ROI align; emit per-class mask
    targets and the class mask (one-hot over foreground classes)."""
    ms = (mask_size if hasattr(mask_size, "__len__")
          else (mask_size, mask_size))
    mh, mw = int(ms[0]), int(ms[1])
    b, r = matches.shape[:2]
    m, hh, ww = gt_masks.shape[1:4]

    sr = sample_ratio if sample_ratio > 0 else 2

    def one_img(rois_i, masks_i, match_i, cls_i):
        def one_roi(roi, mi):
            x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            gy = y1 + (jnp.arange(mh * sr) + 0.5) * rh / (mh * sr)
            gx = x1 + (jnp.arange(mw * sr) + 0.5) * rw / (mw * sr)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            img = masks_i[mi.astype(jnp.int32)][None]       # (1, H, W)
            s = _bilinear_nchw(img, yy.ravel(), xx.ravel())
            s = s.reshape(1, mh, sr, mw, sr)
            return s.mean(axis=(2, 4))[0]                   # (mh, mw)

        targets = jax.vmap(one_roi)(rois_i, match_i)        # (R, mh, mw)
        # broadcast each target to its class slot; class 0 = background
        cls = cls_i.astype(jnp.int32)
        onehot = (jnp.arange(num_classes)[None, :] == cls[:, None]) & \
            (cls[:, None] > 0)
        mask_cls = onehot.astype(rois_i.dtype)[:, :, None, None] * \
            jnp.ones((1, 1, mh, mw), rois_i.dtype)
        mask_targets = targets[:, None] * jnp.ones(
            (1, num_classes, 1, 1), rois_i.dtype)
        return mask_targets, mask_cls

    t, c = jax.vmap(one_img)(rois, gt_masks, matches, cls_targets)
    return t, c


_reg("_contrib_mrcnn_mask_target", _mrcnn_mask_target, nout=2,
     differentiable=False)


# ------------------------------------------------------------- hawkes ll --

def _hawkesll(lda, alpha, beta, state, lags, marks, valid_length,
              max_time):
    """Marked-Hawkes log-likelihood (reference: hawkes_ll.cc, kernel in
    hawkes_ll-inl.h:113). Inputs: lda/mu (N,K), alpha (K,), beta (K,),
    state (N,K), lags (N,T), marks int (N,T), valid_length (N,),
    max_time (N,). Returns (loglik (N,), out_state (N,K))."""
    n, k = lda.shape
    t_len = lags.shape[1]
    marks = marks.astype(jnp.int32)

    def one(mu_i, state_i, lag_i, mark_i, vl_i, mt_i):
        def step(carry, inp):
            state, last, t, ll, j = carry
            lag, mark = inp
            t = t + lag
            d = t - last[mark]
            ed = jnp.exp(-beta[mark] * d)
            lam = mu_i[mark] + alpha[mark] * beta[mark] * state[mark] * ed
            comp = mu_i[mark] * d + alpha[mark] * state[mark] * (1 - ed)
            valid = j < vl_i
            ll = ll + jnp.where(valid, jnp.log(lam) - comp, 0.0)
            state = state.at[mark].set(
                jnp.where(valid, 1 + state[mark] * ed, state[mark]))
            last = last.at[mark].set(jnp.where(valid, t, last[mark]))
            t = jnp.where(valid, t, t - lag)
            return (state, last, t, ll, j + 1), None

        init = (state_i, jnp.zeros(k, lda.dtype),
                jnp.asarray(0.0, lda.dtype), jnp.asarray(0.0, lda.dtype),
                0)
        (state_f, last_f, _, ll, _), _ = lax.scan(
            step, init, (lag_i, mark_i))
        # remaining compensators up to max_time + state decay
        d = mt_i - last_f
        ed = jnp.exp(-beta * d)
        rem = mu_i * d + alpha * state_f * (1 - ed)
        ll = ll - rem.sum()
        return ll, state_f * ed

    ll, out_state = jax.vmap(one)(lda, state, lags, marks,
                                  valid_length, max_time)
    return ll, out_state


_reg("_contrib_hawkesll", _hawkesll, nout=2)
