"""Ragged paged attention for TPU LLM decoding.

The decode half of "Ragged Paged Attention: A High-Performance and
Flexible LLM Inference Kernel for TPU" (PAPERS.md): a batch of
sequences with *different* lengths attends over a paged KV cache — a
fixed pool of ``[num_blocks, block_size, heads, head_dim]`` blocks —
indirected through per-sequence block tables, so no sequence ever owns
contiguous KV storage and the batch shape never depends on the length
mix. One query token per sequence (the continuous-batching decode
shape: ``[max_seqs, 1]``), keys/values gathered block-by-block.

Two paths, gated exactly like :mod:`.flash_attention`:

- ``ragged_attention_reference`` — a gather-based plain-``jnp`` oracle:
  gather every sequence's blocks, mask positions ``>= kv_len``, one
  masked softmax. This is the path the decode engine runs off-TPU and
  the oracle the Pallas kernel is pinned against
  (tests/test_ragged_attention.py).
- ``_ragged_decode_pallas`` — a Pallas kernel, grid
  ``(num_seqs, blocks_per_seq)``: the block table and the ragged
  lengths ride in as SCALAR-PREFETCH operands
  (``pltpu.PrefetchScalarGridSpec``), so each grid step's KV page DMA
  is index-mapped through ``block_tables[i, j]`` before the kernel body
  runs — the gather never materializes in HBM. Online softmax
  (running max / denominator in VMEM scratch, f32) across a sequence's
  block steps; fully-masked blocks (``j*block_size >= kv_len``) skip
  their compute. Off-TPU the same kernel runs in interpret mode.

Lengths semantics: ``kv_lens[i]`` counts the VALID tokens of sequence
``i`` (the current decode token's KV must already be written to its
page). The masking guarantee runs one way: data beyond ``kv_lens[i]``
— and anything in the null block — can never leak into row ``i``'s
output (pinned by the garbage-invisibility test). Rows with
``kv_lens[i] == 0`` are undefined; callers keep inactive rows clamped
to 1 over the null block and DISCARD their outputs — the null block
accumulates stale K/V from padded writes, so those rows are
unspecified values, not zeros.

Multi-token queries (ISSUE 12): the same kernel generalizes from one
query token per sequence to a CHUNK of ``Q`` query tokens per sequence
— ``q`` shaped ``[S, Q, H, D]`` with a scalar-prefetched ``q_lens[i]``
giving each row's valid token count (``0 <= q_lens[i] <= Q``; padded
tail tokens and whole inactive rows produce DISCARDED outputs). Query
token ``t`` of row ``i`` sits at absolute position
``kv_lens[i] - q_lens[i] + t`` and attends CAUSALLY over the paged
history: positions ``<= kv_lens[i] - q_lens[i] + t`` only, masked
inside the kernel, online softmax unchanged. This one shape is
chunked prefill (``q_lens[i]`` prompt tokens whose KV was just
written), decode (``q_lens[i] == 1``) and speculative verify
(``q_lens[i] == K + 1`` draft positions scored in one dispatch) — the
"Ragged Paged Attention" unification (PAPERS.md): prefill and decode
are the same multi-query-token kernel over the paged cache.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register
from .flash_attention import _NEG_INF, _on_tpu


def ragged_attention_reference(q, k_pages, v_pages, block_tables,
                               kv_lens, scale=None):
    """Gather-based oracle. q: (S, H, D); pages: (N, bs, H, D);
    block_tables: (S, MB) int32; kv_lens: (S,) int32."""
    S, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_tables.shape[1]
    s = scale if scale is not None else float(1.0 / (D ** 0.5))
    k = k_pages[block_tables].reshape(S, MB * bs, H, D)
    v = v_pages[block_tables].reshape(S, MB * bs, H, D)
    logits = jnp.einsum("shd,skhd->shk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    pos = jnp.arange(MB * bs, dtype=jnp.int32)
    mask = pos[None, None, :] < kv_lens[:, None, None]
    logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("shk,skhd->shd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ragged_chunk_attention_reference(q, k_pages, v_pages, block_tables,
                                     kv_lens, q_lens, scale=None):
    """Gather-based oracle for the multi-token chunk shape.

    q: (S, Q, H, D); pages: (N, bs, H, D); block_tables: (S, MB)
    int32; kv_lens/q_lens: (S,) int32. Query token ``t`` of row ``i``
    attends over positions ``<= kv_lens[i] - q_lens[i] + t``. Outputs
    for ``t >= q_lens[i]`` are unspecified (callers discard them)."""
    S, Q, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_tables.shape[1]
    s = scale if scale is not None else float(1.0 / (D ** 0.5))
    k = k_pages[block_tables].reshape(S, MB * bs, H, D)
    v = v_pages[block_tables].reshape(S, MB * bs, H, D)
    logits = jnp.einsum("sqhd,skhd->shqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    pos = jnp.arange(MB * bs, dtype=jnp.int32)            # kv position
    qpos = (kv_lens[:, None] - q_lens[:, None]
            + jnp.arange(Q, dtype=jnp.int32)[None, :])    # (S, Q)
    mask = pos[None, None, None, :] <= qpos[:, None, :, None]
    logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    # fully-masked (impossible for valid t; padded t attends somewhere)
    out = jnp.einsum("shqk,skhd->sqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ragged_flat_attention_reference(q, k_pages, v_pages, block_tables,
                                    seq_ids, positions, scale=None,
                                    k_scales=None, v_scales=None):
    """Gather-based oracle for the FLAT ragged layout: ``q`` is a
    packed ``[T, H, D]`` batch of query tokens from MANY sequences —
    token ``t`` belongs to row ``seq_ids[t]`` of ``block_tables`` and
    sits at absolute position ``positions[t]``, attending causally
    over positions ``<= positions[t]`` of ITS sequence's paged
    history. No per-sequence padding: the step computes exactly the
    tokens that exist (prefill chunks, decodes and verify positions
    packed together — the "[total_q_tokens]" shape of the Ragged
    Paged Attention paper). Invalid/padded tokens should carry
    ``seq_ids`` pointing at an all-null table row; their outputs are
    unspecified and must be discarded.

    Quantized pages (ISSUE 13): with ``k_scales``/``v_scales``
    ``(N, bs, H)`` f32, the int8 pages are dequantized per slot+head
    right after the gather — ``k = int8 * scale`` — and everything
    downstream runs in f32 exactly as the float path does."""
    T, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_tables.shape[1]
    s = scale if scale is not None else float(1.0 / (D ** 0.5))
    tbl = block_tables[seq_ids]                       # (T, MB)
    k = k_pages[tbl]
    v = v_pages[tbl]
    if k_scales is not None:
        k = k.astype(jnp.float32) * k_scales[tbl][..., None]
        v = v.astype(jnp.float32) * v_scales[tbl][..., None]
    k = k.reshape(T, MB * bs, H, D)
    v = v.reshape(T, MB * bs, H, D)
    logits = jnp.einsum("thd,tkhd->htk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    pos = jnp.arange(MB * bs, dtype=jnp.int32)
    mask = pos[None, None, :] <= positions[None, :, None]
    logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("htk,tkhd->thd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ----------------------------------------------------------- pallas --


def _flat_kernel(sid_ref, pos_ref, bt_ref, q_ref, k_ref, v_ref,
                 o_ref, acc_ref, m_ref, l_ref, *, scale, block_size,
                 num_blocks):
    """Grid (T, MB): the decode kernel generalized to per-TOKEN
    sequence indirection — the page DMA for grid step (t, j) is
    index-mapped through ``block_tables[seq_ids[t], j]``."""
    t, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    qpos = pos_ref[t]
    base = j * block_size

    @pl.when(base <= qpos)
    def _body():
        q = q_ref[...].astype(jnp.float32)            # (H, D)
        k = k_ref[...].astype(jnp.float32)            # (bs, H, D)
        v = v_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale
        pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s = jnp.where(pos <= qpos, s, _NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnums=(6, 7))
def _ragged_flat_pallas(q, k_pages, v_pages, block_tables, seq_ids,
                        positions, scale, interpret):
    T, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(T, MB),
        in_specs=[
            pl.BlockSpec((None, H, D),
                         lambda t, j, sid, pos, bt: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H, D),
                         lambda t, j, sid, pos, bt:
                         (bt[sid[t], j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H, D),
                         lambda t, j, sid, pos, bt:
                         (bt[sid[t], j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((None, H, D),
                               lambda t, j, sid, pos, bt: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_flat_kernel, scale=scale,
                               block_size=bs, num_blocks=MB)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, H, D), q.dtype),
        interpret=interpret,
    )(seq_ids.astype(jnp.int32), positions.astype(jnp.int32),
      block_tables.astype(jnp.int32), q, k_pages, v_pages)


def _flat_quant_kernel(sid_ref, pos_ref, bt_ref, q_ref, k_ref, v_ref,
                       ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                       scale, block_size, num_blocks):
    """The flat kernel's QUANTIZED-page variant: identical grid and
    online softmax, but the K/V page tiles arrive int8 with per-slot
    per-head f32 scale tiles (same ``bt[sid[t], j]`` index map), and
    dequantization ``int8 * scale`` is fused right where the tile
    lands in VMEM — the f32 pages never materialize in HBM."""
    t, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    qpos = pos_ref[t]
    base = j * block_size

    @pl.when(base <= qpos)
    def _body():
        q = q_ref[...].astype(jnp.float32)            # (H, D)
        k = k_ref[...].astype(jnp.float32) \
            * ks_ref[...][..., None]                  # (bs, H, D)
        v = v_ref[...].astype(jnp.float32) \
            * vs_ref[...][..., None]
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale
        pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s = jnp.where(pos <= qpos, s, _NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnums=(8, 9))
def _ragged_flat_quant_pallas(q, k_pages, v_pages, k_scales, v_scales,
                              block_tables, seq_ids, positions, scale,
                              interpret):
    T, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(T, MB),
        in_specs=[
            pl.BlockSpec((None, H, D),
                         lambda t, j, sid, pos, bt: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H, D),
                         lambda t, j, sid, pos, bt:
                         (bt[sid[t], j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H, D),
                         lambda t, j, sid, pos, bt:
                         (bt[sid[t], j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
            # the scale tiles ride the SAME scalar-prefetched
            # block-table index map as their pages
            pl.BlockSpec((None, bs, H),
                         lambda t, j, sid, pos, bt:
                         (bt[sid[t], j], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H),
                         lambda t, j, sid, pos, bt:
                         (bt[sid[t], j], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((None, H, D),
                               lambda t, j, sid, pos, bt: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_flat_quant_kernel, scale=scale,
                               block_size=bs, num_blocks=MB)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, H, D), jnp.float32),
        interpret=interpret,
    )(seq_ids.astype(jnp.int32), positions.astype(jnp.int32),
      block_tables.astype(jnp.int32), q, k_pages, v_pages,
      k_scales, v_scales)


def ragged_flat_attention(q, k_pages, v_pages, block_tables, seq_ids,
                          positions, scale=None, use_pallas=None,
                          interpret=None, k_scales=None, v_scales=None):
    """Flat-ragged paged attention entry point (packed
    ``[total_q_tokens]`` batch, per-token sequence/position
    indirection). Gated exactly like :func:`ragged_paged_attention`.

    ``k_scales``/``v_scales`` ``(N, bs, H)`` f32 select the QUANTIZED
    page variant: pages are int8 and are dequantized per slot+head
    inside the kernel (fused after the page DMA on the Pallas path,
    right after the gather on the reference path)."""
    if use_pallas is None:
        use_pallas = _on_tpu()
    if scale is None:
        scale = float(1.0 / (q.shape[-1] ** 0.5))
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales or neither")
    if not use_pallas:
        return ragged_flat_attention_reference(
            q, k_pages, v_pages, block_tables, jnp.asarray(seq_ids),
            jnp.asarray(positions), scale, k_scales=k_scales,
            v_scales=v_scales)
    if interpret is None:
        interpret = not _on_tpu()
    if k_scales is not None:
        return _ragged_flat_quant_pallas(
            q, k_pages, v_pages, jnp.asarray(k_scales),
            jnp.asarray(v_scales), jnp.asarray(block_tables),
            jnp.asarray(seq_ids), jnp.asarray(positions),
            float(scale), bool(interpret)).astype(q.dtype)
    return _ragged_flat_pallas(q, k_pages, v_pages,
                               jnp.asarray(block_tables),
                               jnp.asarray(seq_ids),
                               jnp.asarray(positions),
                               float(scale), bool(interpret))


def ragged_flat_attention_sharded(q, k_pages, v_pages, block_tables,
                                  seq_ids, positions, axis_name=None,
                                  scale=None, use_pallas=None,
                                  interpret=None, k_scales=None,
                                  v_scales=None):
    """Head-sharded flat variant for ``shard_map`` bodies (ISSUE 19),
    incl. the quantized-page form: ``q [T, H_local, D]``, pages
    ``(N, bs, H_local, D)`` and scale pools ``(N, bs, H_local)``
    carry ONLY this shard's heads; ``block_tables/seq_ids/positions``
    ride replicated (host-global block accounting).

    Attention is per-head independent and the softmax scale is
    ``1/sqrt(head_dim)`` — never head-count-dependent — so the local
    call IS this shard's full contribution: there is NO collective in
    here. The all-reduce that merges shards belongs to the caller's
    o-projection (fused into the one step program), which keeps this
    kernel dispatch per-shard and collective placement explicit.
    ``axis_name`` is accepted for symmetry/documentation; the scale
    default is pinned to head_dim explicitly so a future head-count
    -dependent rescale can't silently break shard independence."""
    del axis_name  # no collective here by design — see docstring
    if scale is None:
        scale = float(1.0 / (q.shape[-1] ** 0.5))  # head_dim only
    return ragged_flat_attention(
        q, k_pages, v_pages, block_tables, seq_ids, positions,
        scale=scale, use_pallas=use_pallas, interpret=interpret,
        k_scales=k_scales, v_scales=v_scales)


def _chunk_kernel(bt_ref, len_ref, qlen_ref, q_ref, k_ref, v_ref,
                  o_ref, acc_ref, m_ref, l_ref, *, scale, block_size,
                  num_blocks, q_tokens):
    """Grid (S, MB): one (chunk x KV-page) tile per step. Scratch
    carries the online softmax across a row's page steps; the causal
    mask (query t at absolute position kv_len - q_len + t) is applied
    in-kernel so one program covers prefill chunks, decode and
    speculative verify."""
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    kv_len = len_ref[i]
    q_len = qlen_ref[i]
    base = j * block_size

    # a block whose first position is past the LAST query's causal
    # horizon (kv_len - 1) is fully masked for every query token:
    # skip its compute (the page DMA still streams)
    @pl.when(base < kv_len)
    def _body():
        q = q_ref[...].astype(jnp.float32)            # (Q, H, D)
        k = k_ref[...].astype(jnp.float32)            # (bs, H, D)
        v = v_ref[...].astype(jnp.float32)
        # batch over heads: (Q, H, D) x (bs, H, D) -> (H, Q, bs)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32) * scale
        pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2)         # kv position
        qpos = (kv_len - q_len + jax.lax.broadcasted_iota(
            jnp.int32, (1, q_tokens, 1), 1))          # query position
        mask = pos <= qpos                            # (1, Q, bs)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[...]                           # (H, Q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        # early query tokens see NOTHING in later pages: their whole
        # tile row is masked and m stays at _NEG_INF — zero p
        # explicitly instead of trusting exp(-inf - -inf)
        p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=2,
                                                  keepdims=True)
        # (H, Q, bs) x (bs, H, D) batched over H -> (H, Q, D)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = jnp.transpose(
            acc_ref[...] / l_safe, (1, 0, 2)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnums=(6, 7))
def _ragged_chunk_pallas(q, k_pages, v_pages, block_tables, kv_lens,
                         q_lens, scale, interpret):
    S, Q, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, MB),
        in_specs=[
            pl.BlockSpec((None, Q, H, D),
                         lambda i, j, bt, ln, ql: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H, D),
                         lambda i, j, bt, ln, ql: (bt[i, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H, D),
                         lambda i, j, bt, ln, ql: (bt[i, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((None, Q, H, D),
                               lambda i, j, bt, ln, ql: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((H, Q, D), jnp.float32),
            pltpu.VMEM((H, Q, 1), jnp.float32),
            pltpu.VMEM((H, Q, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_chunk_kernel, scale=scale,
                               block_size=bs, num_blocks=MB,
                               q_tokens=Q)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Q, H, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      q_lens.astype(jnp.int32), q, k_pages, v_pages)


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, block_size,
                   num_blocks):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    kv_len = len_ref[i]
    # a block whose first position is past the ragged length is fully
    # masked: skip its compute (the page DMA still streams)
    base = j * block_size

    @pl.when(base < kv_len)
    def _body():
        q = q_ref[...].astype(jnp.float32)            # (H, D)
        k = k_ref[...].astype(jnp.float32)            # (bs, H, D)
        v = v_ref[...].astype(jnp.float32)
        # batch over heads: (H, D) x (bs, H, D) -> (H, bs)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale
        pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)            # (1, bs)
        s = jnp.where(pos < kv_len, s, _NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                        # masked -> 0.0
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1,
                                                  keepdims=True)
        # (H, bs) x (bs, H, D) batched over H -> (H, D)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnums=(5, 6))
def _ragged_decode_pallas(q, k_pages, v_pages, block_tables, kv_lens,
                          scale, interpret):
    S, H, D = q.shape
    bs = k_pages.shape[1]
    MB = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, MB),
        in_specs=[
            pl.BlockSpec((None, H, D), lambda i, j, bt, ln: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            # one KV page per grid step, index-mapped through the
            # scalar-prefetched block table: the DMA for block j of
            # sequence i fetches page block_tables[i, j]
            pl.BlockSpec((None, bs, H, D),
                         lambda i, j, bt, ln: (bt[i, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bs, H, D),
                         lambda i, j, bt, ln: (bt[i, j], 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((None, H, D),
                               lambda i, j, bt, ln: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, scale=scale,
                               block_size=bs, num_blocks=MB)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      q, k_pages, v_pages)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, kv_lens,
                           q_lens=None, scale=None, use_pallas=None,
                           interpret=None):
    """Paged attention entry point — decode AND chunk shapes.

    q: (S, H, D) — one query token per sequence (decode) — or
    (S, Q, H, D) — a chunk of up to Q query tokens per sequence with
    ``q_lens`` (S,) int32 valid counts (chunked prefill / speculative
    verify). k_pages/v_pages: (N, bs, H, D); block_tables: (S, MB)
    int32 page indices (pad unused entries with the null block 0);
    kv_lens: (S,) int32 valid-token counts (>= 1; keep inactive rows
    at 1 over the null block).

    ``use_pallas`` defaults to the flash_attention gate: the Pallas
    kernel on TPU, the gather reference elsewhere. Forcing
    ``use_pallas=True`` off-TPU runs the kernel in interpret mode
    (the parity-test configuration).
    """
    if use_pallas is None:
        use_pallas = _on_tpu()
    if scale is None:
        scale = float(1.0 / (q.shape[-1] ** 0.5))
    chunked = getattr(q, "ndim", len(getattr(q, "shape", ()))) == 4
    if chunked and q_lens is None:
        raise ValueError("chunk-shaped q (S, Q, H, D) requires q_lens")
    if not use_pallas:
        if chunked:
            return ragged_chunk_attention_reference(
                q, k_pages, v_pages, block_tables, kv_lens,
                jnp.asarray(q_lens), scale)
        return ragged_attention_reference(q, k_pages, v_pages,
                                          block_tables, kv_lens, scale)
    if interpret is None:
        interpret = not _on_tpu()
    if chunked:
        return _ragged_chunk_pallas(q, k_pages, v_pages,
                                    jnp.asarray(block_tables),
                                    jnp.asarray(kv_lens),
                                    jnp.asarray(q_lens),
                                    float(scale), bool(interpret))
    return _ragged_decode_pallas(q, k_pages, v_pages,
                                 jnp.asarray(block_tables),
                                 jnp.asarray(kv_lens),
                                 float(scale), bool(interpret))


@register("ragged_paged_attention", differentiable=False)
def _ragged_op(q, k_pages, v_pages, block_tables, kv_lens, *,
               q_lens=None, scale=None, use_pallas=None):
    """Registered paged-attention op (decode + chunk shapes): Pallas
    kernel on TPU, gather reference elsewhere."""
    return ragged_paged_attention(q, k_pages, v_pages, block_tables,
                                  kv_lens, q_lens=q_lens, scale=scale,
                                  use_pallas=use_pallas)
