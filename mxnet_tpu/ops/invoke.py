"""Eager op invocation.

TPU-native replacement of the reference's imperative invoke path
(reference: src/imperative/imperative.cc:98 ``Imperative::Invoke`` →
``SetShapeType`` → ``PushFCompute`` → engine). There is no dependency engine
here: JAX's async dispatch + XLA give the same "Python returns immediately,
device runs later" contract, and read/write ordering is inherent because
arrays are immutable values (mutation = rebinding the buffer).

``apply_op`` is the single chokepoint every generated ``nd.*`` function goes
through — the analogue of ``MXImperativeInvokeEx`` — and is also where
autograd taping happens (reference: ``Imperative::RecordOp``).
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .. import autograd, _rng
from .registry import Operator, get as get_op

__all__ = ["apply_op", "apply_fn", "wrap_out", "as_jax",
           "TRACED_HYPERPARAMS"]

import numpy as _np

_HOST_CB_DEVICE = "unset"


def _host_callback_device():
    """CPU device to reroute host-callback ops to, or None when the
    default platform supports callbacks itself. Probed once: some
    accelerator platforms (tunneled TPUs) reject jax.pure_callback
    outright; platforms that support it keep native placement."""
    global _HOST_CB_DEVICE
    if _HOST_CB_DEVICE != "unset":
        return _HOST_CB_DEVICE
    try:
        if jax.devices()[0].platform == "cpu":
            _HOST_CB_DEVICE = None
            return None
        try:  # probe actual support on the default backend
            jax.pure_callback(
                lambda: _np.zeros((), _np.float32),
                jax.ShapeDtypeStruct((), _np.float32)).block_until_ready()
            _HOST_CB_DEVICE = None
        except Exception:
            cpus = jax.local_devices(backend="cpu")
            _HOST_CB_DEVICE = cpus[0] if cpus else None
    except RuntimeError:
        _HOST_CB_DEVICE = None
    return _HOST_CB_DEVICE

# AMP hook state, mutated by mxnet_tpu.amp (the TPU-native analogue of the
# reference's amp_cast graph-rewrite insertion, python/mxnet/contrib/amp/
# amp.py:283 — here the cast happens at the op-invoke chokepoint instead
# of by patching every generated namespace function).
_AMP = {"active": False, "dtype": None, "lp_ops": frozenset(),
        "f32_ops": frozenset()}


def _amp_cast_inputs(op_name, inputs):
    import numpy as _onp
    NDArray = _ndarray_cls()
    if op_name in _AMP["lp_ops"]:
        target = _AMP["dtype"]
    elif op_name in _AMP["f32_ops"]:
        target = _onp.float32
    else:
        return inputs
    out = []
    for x in inputs:
        if isinstance(x, NDArray) and x.dtype in (_onp.float32,
                                                  _AMP["dtype"]) \
                and x.dtype != target:
            x = x.astype(target)
        out.append(x)
    return out


def _ndarray_cls():
    from ..ndarray.ndarray import NDArray
    return NDArray


def as_jax(x):
    """Unwrap NDArray / coerce array-likes to jax values."""
    NDArray = _ndarray_cls()
    if isinstance(x, NDArray):
        return x._data
    return x  # tracers, jnp arrays, numpy, scalars pass through


def wrap_out(data):
    NDArray = _ndarray_cls()
    return NDArray(data)


def _participating_slots(inputs):
    slots = []
    any_part = False
    for x in inputs:
        s = getattr(x, "_ag_slot", None)
        slots.append(s)
        any_part = any_part or (s is not None)
    return slots, any_part


def apply_fn(fn, inputs: Sequence, nout: int = 1, differentiable: bool = True,
             out=None):
    """Run a pure jax function over NDArray inputs with autograd taping.

    This is the generic path used both by registered ops and by ad-hoc
    differentiable closures (indexing, fused expressions).
    """
    NDArray = _ndarray_cls()
    xs = tuple(as_jax(i) for i in inputs)

    in_slots, any_part = _participating_slots(inputs)
    recorded = (differentiable and autograd.is_recording() and any_part)

    if recorded:
        outs, vjp_fn = jax.vjp(fn, *xs)
    else:
        outs = fn(*xs)

    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)

    results = []
    if out is not None:
        # write-to-output form (reference `out=` kwarg): rebind the
        # destination's buffer; not taped (matches reference kWriteTo refusal
        # to record in-place writes of graph arrays)
        targets = (out,) if isinstance(out, NDArray) else tuple(out)
        for t, o in zip(targets, outs_t):
            t._data = jnp.asarray(o, dtype=t.dtype) if o.dtype != t.dtype else o
            results.append(t)
    else:
        results = [NDArray(o) for o in outs_t]

    if recorded and out is None:
        out_slots = [autograd.new_slot() for _ in results]
        out_avals = [(r.shape, r._data.dtype) for r in results]
        for r, s in zip(results, out_slots):
            r._ag_slot = s
        # fn/xs allow create_graph=True to re-derive this vjp
        # differentiably (autograd._taped_vjp)
        autograd.record_node(vjp_fn, in_slots, out_slots, out_avals,
                             fn=fn, xs=xs)

    return results[0] if single else tuple(results)


def _embedding_sparse_grad(op, inputs, params):
    """Eager Embedding with ``sparse_grad=True``: record a tape node whose
    weight cotangent is a RowSparseNDArray of (looked-up row ids, output
    cotangents) — no (vocab, dim) dense scatter (reference:
    src/operator/tensor/indexing_op.cc EmbeddingOpBackward with
    kRowSparseStorage). Returns None under tracing (jit of a hybridized
    block): there the dense scatter-add vjp is the right XLA program.
    """
    NDArray = _ndarray_cls()
    data, weight = inputs[0], inputs[1]
    if any(isinstance(getattr(x, "_data", x), jax.core.Tracer)
           for x in (data, weight)):
        return None
    from ..ndarray.sparse import RowSparseNDArray

    in_slots, any_part = _participating_slots([data, weight])
    if not any_part:
        return None

    idx = as_jax(data).astype(jnp.int32)
    w = as_jax(weight)
    out_val = jnp.take(w, idx, axis=0)
    result = NDArray(out_val)

    def vjp_fn(dy):
        gw = RowSparseNDArray(dy.reshape(-1, w.shape[-1]), idx.ravel(),
                              w.shape)
        return (None, gw)

    out_slot = autograd.new_slot()
    result._ag_slot = out_slot
    autograd.record_node(vjp_fn, in_slots, [out_slot],
                         [(result.shape, out_val.dtype)])
    return result


# ---------------------------------------------------------------------------
# Single-dispatch optimizer-op path.
#
# Per-step hyperparameters that enter compiled update programs as TRACED
# scalars (weak-typed, exactly like an eager Python-float operand) so lr/wd/
# momentum schedules and LossScaler rescale changes never trigger a
# recompile. Floats OUTSIDE this set (clip_gradient, clip_weights, lower/
# upper bounds) stay static because the impls branch on them in Python.
TRACED_HYPERPARAMS = frozenset({"lr", "wd", "momentum", "rescale_grad"})

_MUTATES_JIT_CACHE = {}

# Set by optimizer.fused while it records an update program: apply_op hands
# mutates-op invocations to the recorder instead of executing them, so one
# host pass over the per-param updater yields the op sequence + scalar
# hyperparameter values that the fused single-dispatch program replays.
_FUSED_RECORDER = threading.local()


def _is_dynamic(v):
    return isinstance(v, jax.core.Tracer) or isinstance(v, jax.Array)


def _split_hyper(params):
    """(static kwargs, traced keys, traced values) for one mutates-op call.
    Only plain floats under TRACED_HYPERPARAMS become traced; everything
    else (bools, ints, None, structural floats) is baked into the compiled
    program and keys the jit cache."""
    static, tkeys, tvals = [], [], []
    for k in sorted(params):
        v = params[k]
        if k in TRACED_HYPERPARAMS and isinstance(v, (float, _np.floating)) \
                and not isinstance(v, bool):
            tkeys.append(k)
            tvals.append(float(v))
        else:
            static.append((k, v))
    return tuple(static), tuple(tkeys), tvals


def _mutates_jit(op, static_kw, traced_keys):
    key = (op.name, static_kw, traced_keys)
    fn = _MUTATES_JIT_CACHE.get(key)
    if fn is None:
        skw = dict(static_kw)
        impl, keys = op.impl, traced_keys

        def call(xs, tvals):
            kw = dict(skw)
            kw.update(zip(keys, tvals))
            return impl(*xs, **kw)

        fn = jax.jit(call)
        _MUTATES_JIT_CACHE[key] = fn
    return fn


def _run_mutates(op, xs, params):
    """Execute a mutates (optimizer update) op as ONE compiled dispatch.

    The impl runs under jax.jit with TRACED_HYPERPARAMS floats passed as
    weak-typed traced scalars: numerics are identical to handing the impl a
    Python float, there is one XLA execution instead of one per jnp
    primitive, and a changed lr/momentum/rescale value reuses the compiled
    program. Falls back to the direct eager impl when a hyperparameter is
    itself a tracer/array (op invoked under an outer trace with traced
    hyperparams, e.g. parallel.ShardedTrainer) or an int (lamb's ``t``
    would bake a new program every step)."""
    for v in params.values():
        if _is_dynamic(v) or (isinstance(v, int) and not isinstance(v, bool)):
            return op.impl(*xs, **params)
    static_kw, tkeys, tvals = _split_hyper(params)
    try:
        hash(static_kw)
    except TypeError:
        return op.impl(*xs, **params)
    return _mutates_jit(op, static_kw, tkeys)(xs, tuple(tvals))


def apply_op(op, inputs: Sequence, params: Optional[dict] = None, out=None):
    """Invoke a registered op on NDArray inputs."""
    if not isinstance(op, Operator):
        op = get_op(op)
    params = dict(params) if params else {}

    if _AMP["active"]:
        inputs = _amp_cast_inputs(op.name, inputs)

    if op.needs_rng and "rng" not in params:
        params["rng"] = _rng.next_key()
    if op.needs_train and "_training" not in params:
        params["_training"] = autograd.is_training()

    if op.mutates:
        recorder = getattr(_FUSED_RECORDER, "rec", None)
        if recorder is not None:
            return recorder.record(op, inputs, params)
        # optimizer-style in-place update: impl returns the new values of the
        # mutated inputs; rebind their buffers (reference: kWriteInplace ops
        # like sgd_update, src/operator/optimizer_op.cc)
        xs = tuple(as_jax(i) for i in inputs)
        outs = _run_mutates(op, xs, params) if not op.variadic \
            else op.impl(list(xs), **params)
        outs_t = (outs,) if not isinstance(outs, (tuple, list)) else tuple(outs)
        results = []
        for k, m in enumerate(op.mutates):
            tgt = inputs[m]
            tgt._data = outs_t[k]
            results.append(tgt)
        return results[0] if len(results) == 1 else tuple(results)

    if op.host_op:
        reroute = _host_callback_device()
        concrete = not any(isinstance(getattr(x, "_data", x),
                                      jax.core.Tracer) for x in inputs)
        if reroute is not None and concrete:
            # platform without host-callback support (e.g. tunneled TPU):
            # run the callback on the CPU backend and device_put the
            # outputs back — eagerly each primitive executes on its
            # operands' backend, and device_put's transpose returns
            # cotangents to the CPU side for the backward callback
            NDArray = _ndarray_cls()
            orig_dev = None
            for x in inputs:
                if isinstance(x, NDArray):
                    try:
                        orig_dev = next(iter(x._data.devices()))
                        break
                    except Exception:
                        pass
            if orig_dev is None:
                orig_dev = jax.devices()[0]
            with jax.default_device(reroute):
                moved = [NDArray(jax.device_put(_np.asarray(x._data),
                                                reroute))
                         if isinstance(x, NDArray) else x
                         for x in inputs]
                for m, x in zip(moved, inputs):
                    if isinstance(x, NDArray):
                        m._ag_slot = getattr(x, "_ag_slot", None)
                if op.variadic:
                    base = lambda *xs: op.impl(list(xs), **params)  # noqa: E731
                else:
                    base = lambda *xs: op.impl(*xs, **params)  # noqa: E731

                def fn(*xs):
                    outs = base(*xs)
                    if isinstance(outs, (tuple, list)):
                        return tuple(jax.device_put(o, orig_dev)
                                     for o in outs)
                    return jax.device_put(outs, orig_dev)

                return apply_fn(fn, moved, nout=op.nout,
                                differentiable=op.differentiable, out=out)

    if ((op.name == "Embedding" and params.get("sparse_grad"))
            or op.name == "_contrib_SparseEmbedding") \
            and autograd.is_recording():
        res = _embedding_sparse_grad(op, inputs, params)
        if res is not None:
            return res

    if op.variadic:
        arrs = list(inputs)
        fn = lambda *xs: op.impl(list(xs), **params)  # noqa: E731
        return apply_fn(fn, arrs, nout=op.nout,
                        differentiable=op.differentiable, out=out)

    fn = lambda *xs: op.impl(*xs, **params)  # noqa: E731
    return apply_fn(fn, inputs, nout=op.nout,
                    differentiable=op.differentiable, out=out)
