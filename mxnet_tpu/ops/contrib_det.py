"""Object-detection contrib ops: the SSD/R-CNN op family, TPU-native.

Reference semantics: src/operator/contrib/multibox_prior.cc:28-70 (anchor
layout and box math), multibox_target.cc:75-280 (bipartite + threshold
matching, negative mining, target encoding :32-55), multibox_detection.cc
:46-215 (decode + per-class NMS), roi_align.cc:144-260 (bilinear-sampled
average pooling), bounding_box.cc (box_iou / box_nms).

TPU redesign: the reference kernels are sequential CPU/CUDA code full of
data-dependent loops and compaction. Here every op is a fixed-shape,
mask-based XLA computation so it jits cleanly:
- the greedy bipartite match runs as a lax.fori_loop over ground-truth
  slots (G is the static label-pad width) on the full (A, G) IoU matrix;
- negative mining replaces the sort-and-take-prefix with a rank
  computation (rank(candidate) < 3*num_pos as a mask);
- NMS keeps everything length-A, marking suppressed rows class=-1
  instead of compacting, exactly matching the reference's output
  convention (it also pads with -1 rows);
- ROIAlign resolves sample_ratio<=0 ("adaptive") to a static 2x2 grid —
  the reference's ceil(roi/pooled) grid is data-dependent and cannot be
  traced; sample_ratio>0 behaves identically to the reference.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_EPS = 1e-12


# ------------------------------------------------------------------ IoU ----

def _corner_iou(a, b):
    """IoU between (..., A, 4) and (..., G, 4) corner boxes -> (..., A, G)."""
    ax1, ay1, ax2, ay2 = [a[..., :, None, i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., None, :, i] for i in range(4)]
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, _EPS)


@register("_contrib_box_iou")
def _box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference: bounding_box.cc _contrib_box_iou).
    lhs (..., N, 4), rhs (..., M, 4) -> (..., N, M)."""
    if format == "center":
        def to_corner(b):
            x, y, w, h = (b[..., 0], b[..., 1], b[..., 2], b[..., 3])
            return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                             axis=-1)
        lhs, rhs = to_corner(lhs), to_corner(rhs)
    return _corner_iou(lhs, rhs)


# ---------------------------------------------------------- MultiBoxPrior --

@register("_contrib_MultiBoxPrior", differentiable=False)
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation (reference: multibox_prior.cc:28-70).

    data: (N, C, H, W) feature map (only H/W used). Returns
    (1, H*W*(num_sizes+num_ratios-1), 4) corner boxes. Per location the
    anchor order matches the reference: all sizes at ratios[0], then
    sizes[0] at ratios[1:]. Note the reference's aspect handling scales
    w by H/W (anchors square in *pixel* space for ratio 1).
    """
    sizes = tuple(float(s) for s in (sizes if hasattr(sizes, "__len__")
                                     else (sizes,)))
    ratios = tuple(float(r) for r in (ratios if hasattr(ratios, "__len__")
                                      else (ratios,)))
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # (H,W,2)

    wh = []
    r0 = ratios[0] ** 0.5
    for s in sizes:
        wh.append((s * h / w * r0 / 2, s / r0 / 2))
    for r in ratios[1:]:
        rs = r ** 0.5
        wh.append((sizes[0] * h / w * rs / 2, sizes[0] / rs / 2))
    wh = jnp.asarray(wh, jnp.float32)                              # (K, 2)

    cxy = cyx[:, :, None, ::-1]                                    # (H,W,1,2)
    boxes = jnp.concatenate([cxy - wh[None, None], cxy + wh[None, None]],
                            axis=-1)                               # (H,W,K,4)
    boxes = boxes.reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


# --------------------------------------------------------- MultiBoxTarget --

def _encode_loc(anchors, gt):
    """Offset encoding (reference: multibox_target.cc:32-55).
    anchors (A, 4) corner, gt (A, 4) matched gt corner -> (A, 4)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], _EPS)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], _EPS)
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    return gx, gy, gw, gh, ax, ay, aw, ah


def _match_one(anchors, label, cls_pred, overlap_threshold,
               negative_mining_ratio, negative_mining_thresh,
               minimum_negative_samples, variances, ignore_label):
    """One batch element. anchors (A,4); label (G,6) [cls,x1,y1,x2,y2,...];
    cls_pred (C, A) logits. Returns loc_target (A,4), loc_mask (A,4),
    cls_target (A,)."""
    A = anchors.shape[0]
    G = label.shape[0]
    valid_gt = label[:, 0] >= 0                                    # (G,)
    iou = _corner_iou(anchors, label[:, 1:5])                      # (A, G)
    iou = jnp.where(valid_gt[None, :], iou, -1.0)

    # --- stage 1: greedy bipartite match (one anchor per gt), G rounds ---
    def body(_, state):
        matched_gt, anchor_used, gt_used = state
        m = jnp.where(anchor_used[:, None] | gt_used[None, :], -1.0, iou)
        flat = jnp.argmax(m)
        aj, gk = flat // G, flat % G
        ok = m[aj, gk] > 1e-6
        matched_gt = jnp.where(ok, matched_gt.at[aj].set(gk), matched_gt)
        anchor_used = jnp.where(ok, anchor_used.at[aj].set(True),
                                anchor_used)
        gt_used = jnp.where(ok, gt_used.at[gk].set(True), gt_used)
        return matched_gt, anchor_used, gt_used

    matched_gt = jnp.full((A,), -1, jnp.int32)
    state = (matched_gt, jnp.zeros((A,), bool), jnp.zeros((G,), bool))
    matched_gt, anchor_pos, _ = lax.fori_loop(0, G, body, state)

    # --- stage 2: threshold match for the rest --------------------------
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)            # (A,)
    best_iou = jnp.max(iou, axis=1)
    thr_pos = (~anchor_pos) & (best_iou > overlap_threshold) \
        if overlap_threshold > 0 else jnp.zeros((A,), bool)
    positive = anchor_pos | thr_pos
    matched_gt = jnp.where(anchor_pos, matched_gt, best_gt)

    # --- negative selection ---------------------------------------------
    num_pos = jnp.sum(positive)
    if negative_mining_ratio > 0:
        # hard negatives: lowest background probability first
        logits = cls_pred.T                                        # (A, C)
        bg_prob = jax.nn.softmax(logits.astype(jnp.float32),
                                 axis=-1)[:, 0]
        candidate = (~positive) & (best_iou < negative_mining_thresh)
        num_neg = jnp.maximum(
            (num_pos * negative_mining_ratio).astype(jnp.int32),
            minimum_negative_samples)
        num_neg = jnp.minimum(num_neg, A - num_pos)
        score = jnp.where(candidate, bg_prob, jnp.inf)
        order = jnp.argsort(score)                # ascending: hardest first
        rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A))
        negative = candidate & (rank < num_neg)
    else:
        negative = ~positive

    # --- targets ---------------------------------------------------------
    gt_boxes = label[matched_gt, 1:5]                              # (A, 4)
    gx, gy, gw, gh, ax, ay, aw, ah = _encode_loc(anchors, gt_boxes)
    v0, v1, v2, v3 = variances
    loc = jnp.stack([(gx - ax) / aw / v0, (gy - ay) / ah / v1,
                     jnp.log(gw / aw) / v2, jnp.log(gh / ah) / v3],
                    axis=-1)
    loc_mask = positive[:, None] & jnp.ones((A, 4), bool)
    loc_target = jnp.where(loc_mask, loc, 0.0)

    gt_cls = label[matched_gt, 0] + 1.0            # 0 = background
    cls_target = jnp.where(positive, gt_cls,
                           jnp.where(negative, 0.0, ignore_label))
    # no valid gt: everything stays at its init value — loc 0, mask 0,
    # cls ignore_label (reference: multibox_target-inl.h:120-123)
    any_gt = jnp.any(valid_gt)
    return (jnp.where(any_gt, loc_target, 0.0),
            jnp.where(any_gt, loc_mask.astype(anchors.dtype), 0.0),
            jnp.where(any_gt, cls_target, ignore_label))


@register("_contrib_MultiBoxTarget", nout=3, differentiable=False)
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5,
                     minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """Training-target assignment (reference: multibox_target.cc:75-280).

    anchor (1, A, 4); label (N, G, >=5) rows [cls, x1, y1, x2, y2, ...]
    padded with -1; cls_pred (N, C, A). Returns loc_target (N, A*4),
    loc_mask (N, A*4), cls_target (N, A).
    """
    anchors = anchor.reshape(-1, 4)
    f = partial(_match_one, overlap_threshold=overlap_threshold,
                negative_mining_ratio=negative_mining_ratio,
                negative_mining_thresh=negative_mining_thresh,
                minimum_negative_samples=minimum_negative_samples,
                variances=tuple(variances), ignore_label=ignore_label)
    loc_t, loc_m, cls_t = jax.vmap(
        lambda lb, cp: f(anchors, lb, cp))(label, cls_pred)
    n = label.shape[0]
    return (loc_t.reshape(n, -1).astype(anchor.dtype),
            loc_m.reshape(n, -1).astype(anchor.dtype),
            cls_t.astype(anchor.dtype))


# ------------------------------------------------------ MultiBoxDetection --

def _decode_boxes(anchors, loc_pred, variances, clip):
    """Offset decoding (reference: multibox_detection.cc:46-72).
    anchors (A, 4), loc_pred (A, 4) -> corner boxes (A, 4)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    v0, v1, v2, v3 = variances
    ox = loc_pred[:, 0] * v0 * aw + ax
    oy = loc_pred[:, 1] * v1 * ah + ay
    ow = jnp.exp(loc_pred[:, 2] * v2) * aw / 2
    oh = jnp.exp(loc_pred[:, 3] * v3) * ah / 2
    boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _nms_mask(cls_ids, boxes, keep_in, nms_threshold, force_suppress):
    """Sequential suppression on score-sorted entries; O(K) lax steps on
    the (K, K) IoU matrix."""
    K = cls_ids.shape[0]
    iou = _corner_iou(boxes, boxes)
    idx = jnp.arange(K)

    def body(i, keep):
        same = jnp.full((K,), True) if force_suppress else \
            (cls_ids == cls_ids[i])
        sup = keep[i] & (iou[i] >= nms_threshold) & same & (idx > i)
        return keep & ~sup

    return lax.fori_loop(0, K, body, keep_in)


def _detect_one(cls_prob, loc_pred, anchors, threshold, clip, variances,
                nms_threshold, force_suppress, nms_topk, background_id):
    C, A = cls_prob.shape
    boxes = _decode_boxes(anchors, loc_pred.reshape(A, 4), variances, clip)
    fg_mask = jnp.arange(C) != background_id
    fg = jnp.where(fg_mask[:, None], cls_prob, -jnp.inf)          # (C, A)
    score = jnp.max(fg, axis=0)
    raw_id = jnp.argmax(fg, axis=0)
    # reference convention: returned ids are 0-based foreground ids
    # (background excluded); with background_id=0 that is raw_id - 1
    cls_id = jnp.where(raw_id > background_id, raw_id - 1,
                       raw_id).astype(jnp.float32)
    valid = score >= threshold
    cls_id = jnp.where(valid, cls_id, -1.0)

    order = jnp.argsort(jnp.where(valid, -score, jnp.inf))
    cls_s, score_s, boxes_s = cls_id[order], score[order], boxes[order]
    keep = cls_s >= 0
    if 0 < nms_threshold <= 1:
        # nms_topk is static: slice to the top-K candidates so the IoU
        # matrix is (K, K), not (A, A) — for SSD-300 (A=8732, topk=400)
        # that is ~475x less memory and ~22x fewer sequential steps
        k = min(nms_topk, A) if nms_topk > 0 else A
        keep_k = _nms_mask(cls_s[:k], boxes_s[:k], keep[:k],
                           nms_threshold, force_suppress)
        keep = jnp.zeros_like(keep).at[:k].set(keep_k)
    elif nms_topk > 0:
        keep = keep & (jnp.arange(A) < nms_topk)
    cls_s = jnp.where(keep, cls_s, -1.0)
    return jnp.concatenate([cls_s[:, None], score_s[:, None], boxes_s],
                           axis=-1)                               # (A, 6)


@register("_contrib_MultiBoxDetection", differentiable=False)
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0,
                        nms_threshold=0.5, force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + NMS (reference: multibox_detection.cc:100-215).

    cls_prob (N, C, A) softmax probs (class 0 = background); loc_pred
    (N, A*4); anchor (1, A, 4). Returns (N, A, 6) rows
    [class_id, score, x1, y1, x2, y2], suppressed/empty rows class_id=-1,
    sorted by score like the reference.
    """
    anchors = anchor.reshape(-1, 4)
    f = partial(_detect_one, anchors=anchors, threshold=threshold,
                clip=clip, variances=tuple(variances),
                nms_threshold=nms_threshold,
                force_suppress=force_suppress, nms_topk=nms_topk,
                background_id=background_id)
    return jax.vmap(lambda cp, lp: f(cp, lp))(
        cls_prob, loc_pred).astype(cls_prob.dtype)


# ----------------------------------------------------------------- NMS -----

@register("_contrib_box_nms")
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1,
             force_suppress=False, in_format="corner",
             out_format="corner"):
    """Generic box NMS (reference: bounding_box.cc _contrib_box_nms).
    data (..., N, K) with score at score_index, boxes at
    coord_start:coord_start+4, optional class at id_index. Suppressed
    rows are overwritten with -1 (the reference convention).
    """
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])

    def one(d):
        n = d.shape[0]
        score = d[:, score_index]
        boxes = d[:, coord_start:coord_start + 4]
        if in_format == "center":
            x, y, w, h = boxes.T
            boxes = jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                              axis=-1)
        ids = d[:, id_index] if id_index >= 0 else jnp.zeros((n,))
        valid = score > valid_thresh
        order = jnp.argsort(jnp.where(valid, -score, jnp.inf))
        d_s, boxes_s, ids_s = d[order], boxes[order], ids[order]
        keep = valid[order]
        k = min(topk, n) if topk > 0 else n     # bound the IoU matrix
        keep = keep & (jnp.arange(n) < k)
        keep_k = _nms_mask(jnp.where(keep[:k], ids_s[:k], -1.0),
                           boxes_s[:k], keep[:k], overlap_thresh,
                           force_suppress or id_index < 0)
        keep = jnp.zeros_like(keep).at[:k].set(keep_k)
        out = jnp.where(keep[:, None], d_s, -1.0)
        if out_format != in_format:
            b = out[:, coord_start:coord_start + 4]
            if out_format == "center":
                conv = jnp.stack([(b[:, 0] + b[:, 2]) / 2,
                                  (b[:, 1] + b[:, 3]) / 2,
                                  b[:, 2] - b[:, 0],
                                  b[:, 3] - b[:, 1]], axis=-1)
            else:  # center -> corner
                conv = jnp.stack([b[:, 0] - b[:, 2] / 2,
                                  b[:, 1] - b[:, 3] / 2,
                                  b[:, 0] + b[:, 2] / 2,
                                  b[:, 1] + b[:, 3] / 2], axis=-1)
            out = out.at[:, coord_start:coord_start + 4].set(
                jnp.where(keep[:, None], conv, -1.0))
        return out

    return jax.vmap(one)(flat).reshape(shape)


# ------------------------------------------------------------- ROIAlign ----

@register("_contrib_ROIAlign")
def _roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROI align (reference: roi_align.cc:144-260).

    data (N, C, H, W); rois (R, 5) rows [batch_idx, x1, y1, x2, y2] in
    image coordinates. Returns (R, C, PH, PW) (or (R, C/(PH*PW), PH, PW)
    position-sensitive). sample_ratio<=0 resolves to a static 2x2 grid
    (the reference's adaptive grid is data-dependent; see module doc).
    Gradients flow to ``data`` through the bilinear gathers.
    """
    ph, pw = (pooled_size if hasattr(pooled_size, "__len__")
              else (pooled_size, pooled_size))
    sr = sample_ratio if sample_ratio > 0 else 2
    N, C, H, W = data.shape
    offset = 0.5 if aligned else 0.0

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:  # legacy: force malformed ROIs to be 1x1
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bh, bw = rh / ph, rw / pw
        # sample grid: (PH*sr, PW*sr) bilinear points
        gy = y1 + (jnp.arange(ph * sr) + 0.5) * bh / sr
        gx = x1 + (jnp.arange(pw * sr) + 0.5) * bw / sr

        img = data[bidx]                                          # (C, H, W)

        def bilinear(y, x):
            y = jnp.clip(y, 0.0, H - 1.0)
            x = jnp.clip(x, 0.0, W - 1.0)
            y0 = jnp.floor(y).astype(jnp.int32)
            x0 = jnp.floor(x).astype(jnp.int32)
            y1i = jnp.minimum(y0 + 1, H - 1)
            x1i = jnp.minimum(x0 + 1, W - 1)
            wy = y - y0
            wx = x - x0
            g = lambda yy, xx: img[:, yy, xx]                     # noqa: E731
            return ((1 - wy) * (1 - wx))[None] * g(y0, x0) + \
                ((1 - wy) * wx)[None] * g(y0, x1i) + \
                (wy * (1 - wx))[None] * g(y1i, x0) + \
                (wy * wx)[None] * g(y1i, x1i)

        yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
        samples = bilinear(yy.ravel(), xx.ravel())                # (C, P)
        samples = samples.reshape(C, ph, sr, pw, sr)
        pooled = samples.mean(axis=(2, 4))                        # (C,PH,PW)
        if position_sensitive:
            cc = C // (ph * pw)
            pooled = pooled.reshape(cc, ph, pw, ph, pw)
            pooled = pooled[:, jnp.arange(ph)[:, None], jnp.arange(pw)[None,
                            :], jnp.arange(ph)[:, None],
                            jnp.arange(pw)[None, :]]
        return pooled

    return jax.vmap(one)(rois).astype(data.dtype)
