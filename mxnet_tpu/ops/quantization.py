"""INT8 quantization operators.

Reference: src/operator/quantization/ (quantize.cc, quantize_v2.cc,
dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc). Semantics kept:

- int8 is SYMMETRIC: scale = 127 / threshold with threshold =
  max(|min|, |max|); value v -> round(v * scale) in [-127, 127].
- uint8 is AFFINE over [min, max] with 255 steps.
- quantized_conv / quantized_fully_connected accumulate int8 x int8 into
  int32 on the MXU (lax preferred_element_type=int32) and return the
  int32 accumulator plus its float range, exactly like the reference's
  kernels; requantize folds int32 -> int8 given calibrated ranges.

Every op returns (out, out_min, out_max) like the reference so the
range bookkeeping composes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .flash_attention import _on_tpu
from .registry import _REGISTRY, Operator, alias, register


def _reg(name, fn, **kw):
    _REGISTRY[name] = Operator(name, fn, **kw)


def _thresh(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


def _quantize(data, min_range, max_range, out_type="int8"):
    """Reference: quantize.cc (_contrib_quantize)."""
    mn = jnp.asarray(min_range).reshape(())
    mx = jnp.asarray(max_range).reshape(())
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(mx - mn, 1e-30)
        q = jnp.clip(jnp.round((data - mn) * scale), 0, 255)\
            .astype(jnp.uint8)
        return q, mn, mx
    t = _thresh(mn, mx)
    scale = 127.0 / jnp.maximum(t, 1e-30)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, -t, t


_reg("_contrib_quantize", _quantize, nout=3, differentiable=False)


def _quantize_v2(data, min_calib_range=None, max_calib_range=None,
                 out_type="int8"):
    """Reference: quantize_v2.cc — computes the range from the data when
    no calibrated range is given."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data)
        mx = jnp.max(data)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(data, mn, mx, out_type=out_type)


_reg("_contrib_quantize_v2", _quantize_v2, nout=3, differentiable=False)


def _dequantize(qdata, min_range, max_range, out_type="float32"):
    """Reference: dequantize.cc."""
    mn = jnp.asarray(min_range).reshape(())
    mx = jnp.asarray(max_range).reshape(())
    if qdata.dtype == jnp.uint8:
        scale = jnp.maximum(mx - mn, 1e-30) / 255.0
        return qdata.astype(jnp.float32) * scale + mn
    t = _thresh(mn, mx)
    return qdata.astype(jnp.float32) * (t / 127.0)


_reg("_contrib_dequantize", _dequantize, differentiable=False)


def _requantize(qdata, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    """int32 accumulator -> int8 (reference: requantize.cc). The int32
    range [min_range, max_range] is the product-range bookkeeping from
    the quantized op; the calibrated range decides the int8 scale."""
    real = _dequantize(qdata.astype(jnp.float32), min_range, max_range) \
        if qdata.dtype != jnp.int32 else \
        qdata.astype(jnp.float32) * (_thresh(
            jnp.asarray(min_range).reshape(()),
            jnp.asarray(max_range).reshape(())) / (127.0 * 127.0))
    if min_calib_range is None:
        mn, mx = jnp.min(real), jnp.max(real)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(real, mn, mx)


_reg("_contrib_requantize", _requantize, nout=3, differentiable=False)


def _quantized_fully_connected(qx, qw, x_scale=1.0, w_scale=1.0,
                               num_hidden=0):
    """int8 x int8 -> int32 dense (reference:
    quantized_fully_connected.cc). Returns the fp32 result scaled back:
    out = (qx @ qw.T) * (x_scale * w_scale); w_scale may be a per-row
    (per-output-channel) vector — finer than the reference's per-tensor
    scale."""
    acc = lax.dot_general(qx, qw, (((qx.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    scale = jnp.asarray(x_scale) * jnp.asarray(w_scale)
    return acc.astype(jnp.float32) * scale


_reg("_contrib_quantized_fully_connected", _quantized_fully_connected,
     differentiable=False)


def _quantized_conv(qx, qw, kernel=None, stride=None, pad=None,
                    num_filter=0, layout="NHWC", x_scale=1.0, w_scale=1.0):
    """int8 conv with int32 accumulation (reference: quantized_conv.cc);
    NHWC/HWIO only (the TPU-native layout)."""
    nd = qx.ndim - 2
    stride = stride or (1,) * nd
    pad = pad or (0,) * nd
    acc = lax.conv_general_dilated(
        qx, qw, window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    scale = jnp.asarray(x_scale) * jnp.asarray(w_scale)
    return acc.astype(jnp.float32) * scale


_reg("_contrib_quantized_conv", _quantized_conv, differentiable=False)


# ------------------------------------------------ quantized op family --
# reference: src/operator/quantization/quantized_activation.cc,
# quantized_pooling.cc, quantized_flatten.cc, quantized_concat.cc,
# quantized_elemwise_add.cc / _mul.cc, quantized_batch_norm.cc,
# quantized_indexing_op.cc, calibrate.cc. Every op keeps the
# (values, min, max) triple contract.

def _quantized_act(data, min_data, max_data, act_type="relu"):
    assert act_type == "relu", "int8 activation supports relu"
    zero = jnp.zeros((), data.dtype)
    return jnp.maximum(data, zero), jnp.maximum(
        jnp.asarray(min_data).reshape(()), 0.0), \
        jnp.asarray(max_data).reshape(())


_reg("_contrib_quantized_act", _quantized_act, nout=3,
     differentiable=False)


def _quantized_pooling(data, min_data, max_data, kernel=None, stride=None,
                       pad=None, pool_type="max", global_pool=False,
                       layout="NCHW"):
    from .nn import _pooling
    out = _pooling(data.astype(jnp.float32), kernel=kernel, stride=stride,
                   pad=pad, pool_type=pool_type, global_pool=global_pool,
                   layout=layout)
    if pool_type == "max":
        out = out.astype(data.dtype)      # exact for max
    else:
        out = jnp.round(out).astype(data.dtype)
    return out, jnp.asarray(min_data).reshape(()), \
        jnp.asarray(max_data).reshape(())


_reg("_contrib_quantized_pooling", _quantized_pooling, nout=3,
     differentiable=False)


def _quantized_flatten(data, min_data, max_data):
    return data.reshape(data.shape[0], -1), \
        jnp.asarray(min_data).reshape(()), \
        jnp.asarray(max_data).reshape(())


_reg("_contrib_quantized_flatten", _quantized_flatten, nout=3,
     differentiable=False)


def _quantized_concat(arrays, num_args=1, dim=1):
    """Inputs: data0..dataN, min0..maxN interleaved per the reference
    (data..., min..., max...). Requantizes every part to the widest
    range, then concatenates."""
    n = len(arrays) // 3
    datas, mins, maxs = arrays[:n], arrays[n:2 * n], arrays[2 * n:]
    ts = [jnp.maximum(jnp.abs(mn.reshape(())), jnp.abs(mx.reshape(())))
          for mn, mx in zip(mins, maxs)]
    t_out = ts[0]
    for t in ts[1:]:
        t_out = jnp.maximum(t_out, t)
    parts = []
    for d, t in zip(datas, ts):
        real = d.astype(jnp.float32) * (t / 127.0)
        parts.append(jnp.clip(jnp.round(real / (t_out / 127.0)),
                              -127, 127).astype(jnp.int8))
    return jnp.concatenate(parts, axis=int(dim)), -t_out, t_out


_REGISTRY["_contrib_quantized_concat"] = Operator(
    "_contrib_quantized_concat", _quantized_concat, nout=3,
    variadic=True, differentiable=False)


def _quantized_elemwise(op):
    def impl(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
        tl = jnp.maximum(jnp.abs(lhs_min.reshape(())),
                         jnp.abs(lhs_max.reshape(())))
        tr = jnp.maximum(jnp.abs(rhs_min.reshape(())),
                         jnp.abs(rhs_max.reshape(())))
        a = lhs.astype(jnp.float32) * (tl / 127.0)
        b = rhs.astype(jnp.float32) * (tr / 127.0)
        real = op(a, b)
        t = jnp.maximum(jnp.max(jnp.abs(real)), 1e-30)
        q = jnp.clip(jnp.round(real / (t / 127.0)), -127, 127)\
            .astype(jnp.int8)
        return q, -t, t
    return impl


_reg("_contrib_quantized_elemwise_add",
     _quantized_elemwise(lambda a, b: a + b), nout=3,
     differentiable=False)
_reg("_contrib_quantized_elemwise_mul",
     _quantized_elemwise(lambda a, b: a * b), nout=3,
     differentiable=False)


def _quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                          min_data=None, max_data=None, eps=1e-3,
                          min_calib_range=None, max_calib_range=None,
                          **kw):
    t_in = jnp.maximum(jnp.abs(min_data.reshape(())),
                       jnp.abs(max_data.reshape(())))
    x = data.astype(jnp.float32) * (t_in / 127.0)
    inv = 1.0 / jnp.sqrt(moving_var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    out = (x - moving_mean.reshape(shape)) * \
        (inv * gamma).reshape(shape) + beta.reshape(shape)
    if min_calib_range is not None:
        t = jnp.maximum(abs(float(min_calib_range)),
                        abs(float(max_calib_range)))
    else:
        t = jnp.maximum(jnp.max(jnp.abs(out)), 1e-30)
    q = jnp.clip(jnp.round(out / (t / 127.0)), -127, 127)\
        .astype(jnp.int8)
    return q, -t, t


_reg("_contrib_quantized_batch_norm", _quantized_batch_norm, nout=3,
     differentiable=False)


def _quantized_embedding(data, weight, min_weight, max_weight,
                         input_dim=0, output_dim=0, dtype="float32",
                         **kw):
    out = jnp.take(weight, data.astype(jnp.int32), axis=0)
    return out, jnp.asarray(min_weight).reshape(()), \
        jnp.asarray(max_weight).reshape(())


_reg("_contrib_quantized_embedding", _quantized_embedding, nout=3,
     differentiable=False)


# ------------------------------------- weight-only serving matmuls --
# ISSUE 20: the serving stack's per-output-channel WEIGHT-ONLY
# quantization (activations stay f32; weights are int8/fp8-e4m3 with
# an f32 scale per output column). Unlike the reference's int8×int8
# ops above, the contraction here runs in f32 on the MXU with the
# dequant FUSED into the matmul — ``(x @ W_q.astype(f32)) * s`` — so
# the f32 weight matrix never materializes in HBM. Gated exactly like
# ragged_attention: plain-jnp reference off-TPU (and as the oracle),
# Pallas kernel on TPU with the int8/fp8 tile dequantized in VMEM.


def quantized_matmul_reference(x, qw, w_scale):
    """Oracle: ``x [T, K] f32 @ qw [K, N] int8/fp8`` with per-output-
    channel ``w_scale [N]`` f32. The scale factors out of each output
    column's contraction, so scaling AFTER the accumulation is the
    same quantity with one multiply per output instead of per
    weight."""
    return (x @ qw.astype(jnp.float32)) * w_scale


def _wq_matmul_kernel(x_ref, qw_ref, s_ref, o_ref):
    # dequant fused in VMEM: the quantized tile and its channel scales
    # are widened to f32 right before the MXU contraction — HBM only
    # ever holds the 1-byte weights
    w = qw_ref[...].astype(jnp.float32) * s_ref[...]
    o_ref[...] = x_ref[...].astype(jnp.float32) @ w


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _wq_matmul_pallas(x, qw, w_scale, block_t, block_n, interpret):
    T, K = x.shape
    N = qw.shape[1]
    grid = (pl.cdiv(T, block_t), pl.cdiv(N, block_n))
    return pl.pallas_call(
        _wq_matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_t, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((T, N), jnp.float32),
        interpret=interpret,
    )(x, qw, w_scale.reshape(1, N))


def quantized_matmul(x, qw, w_scale, use_pallas=None, interpret=None,
                     block_t=None, block_n=None):
    """Per-output-channel weight-only quantized matmul:
    ``out[t, c] = (sum_k x[t, k] * qw[k, c]) * w_scale[c]``.

    x: f32 ``[T, K]``; qw: int8 or fp8-e4m3 ``[K, N]``; w_scale: f32
    ``[N]`` (``serving.llm.quant.quantize_leaf`` scales). Gating as in
    :mod:`.ragged_attention`: ``use_pallas=None`` picks the Pallas
    kernel on TPU and the jnp reference elsewhere; ``interpret`` runs
    the kernel in interpret mode for off-TPU parity tests."""
    if use_pallas is None:
        use_pallas = _on_tpu()
    if not use_pallas:
        return quantized_matmul_reference(x, qw, w_scale)
    if interpret is None:
        interpret = not _on_tpu()
    T, K = x.shape
    N = qw.shape[1]
    bt = int(block_t) if block_t else min(T, 256)
    bn = int(block_n) if block_n else min(N, 256)
    return _wq_matmul_pallas(x, qw, w_scale, bt, bn, bool(interpret))


_reg("_contrib_quantized_matmul", quantized_matmul,
     differentiable=False)
alias("quantized_matmul", "_contrib_quantized_matmul")


def _calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """KL-optimal threshold from a histogram (reference: calibrate.cc
    _contrib_calibrate_entropy); returns (min, max) calib range."""
    import numpy as _onp
    from ..contrib.quantization import optimal_threshold
    t = optimal_threshold(_onp.asarray(hist), _onp.asarray(hist_edges),
                          num_quantized_bins=int(num_quantized_bins))
    return jnp.asarray(-t, jnp.float32), jnp.asarray(t, jnp.float32)


_reg("_contrib_calibrate_entropy", _calibrate_entropy, nout=2,
     host_op=True, differentiable=False)
