"""INT8 quantization operators.

Reference: src/operator/quantization/ (quantize.cc, quantize_v2.cc,
dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc). Semantics kept:

- int8 is SYMMETRIC: scale = 127 / threshold with threshold =
  max(|min|, |max|); value v -> round(v * scale) in [-127, 127].
- uint8 is AFFINE over [min, max] with 255 steps.
- quantized_conv / quantized_fully_connected accumulate int8 x int8 into
  int32 on the MXU (lax preferred_element_type=int32) and return the
  int32 accumulator plus its float range, exactly like the reference's
  kernels; requantize folds int32 -> int8 given calibrated ranges.

Every op returns (out, out_min, out_max) like the reference so the
range bookkeeping composes.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import _REGISTRY, Operator, alias, register


def _reg(name, fn, **kw):
    _REGISTRY[name] = Operator(name, fn, **kw)


def _thresh(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


def _quantize(data, min_range, max_range, out_type="int8"):
    """Reference: quantize.cc (_contrib_quantize)."""
    mn = jnp.asarray(min_range).reshape(())
    mx = jnp.asarray(max_range).reshape(())
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(mx - mn, 1e-30)
        q = jnp.clip(jnp.round((data - mn) * scale), 0, 255)\
            .astype(jnp.uint8)
        return q, mn, mx
    t = _thresh(mn, mx)
    scale = 127.0 / jnp.maximum(t, 1e-30)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, -t, t


_reg("_contrib_quantize", _quantize, nout=3, differentiable=False)


def _quantize_v2(data, min_calib_range=None, max_calib_range=None,
                 out_type="int8"):
    """Reference: quantize_v2.cc — computes the range from the data when
    no calibrated range is given."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data)
        mx = jnp.max(data)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(data, mn, mx, out_type=out_type)


_reg("_contrib_quantize_v2", _quantize_v2, nout=3, differentiable=False)


def _dequantize(qdata, min_range, max_range, out_type="float32"):
    """Reference: dequantize.cc."""
    mn = jnp.asarray(min_range).reshape(())
    mx = jnp.asarray(max_range).reshape(())
    if qdata.dtype == jnp.uint8:
        scale = jnp.maximum(mx - mn, 1e-30) / 255.0
        return qdata.astype(jnp.float32) * scale + mn
    t = _thresh(mn, mx)
    return qdata.astype(jnp.float32) * (t / 127.0)


_reg("_contrib_dequantize", _dequantize, differentiable=False)


def _requantize(qdata, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    """int32 accumulator -> int8 (reference: requantize.cc). The int32
    range [min_range, max_range] is the product-range bookkeeping from
    the quantized op; the calibrated range decides the int8 scale."""
    real = _dequantize(qdata.astype(jnp.float32), min_range, max_range) \
        if qdata.dtype != jnp.int32 else \
        qdata.astype(jnp.float32) * (_thresh(
            jnp.asarray(min_range).reshape(()),
            jnp.asarray(max_range).reshape(())) / (127.0 * 127.0))
    if min_calib_range is None:
        mn, mx = jnp.min(real), jnp.max(real)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(real, mn, mx)


_reg("_contrib_requantize", _requantize, nout=3, differentiable=False)


def _quantized_fully_connected(qx, qw, x_scale=1.0, w_scale=1.0,
                               num_hidden=0):
    """int8 x int8 -> int32 dense (reference:
    quantized_fully_connected.cc). Returns the fp32 result scaled back:
    out = (qx @ qw.T) * (x_scale * w_scale); w_scale may be a per-row
    (per-output-channel) vector — finer than the reference's per-tensor
    scale."""
    acc = lax.dot_general(qx, qw, (((qx.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    scale = jnp.asarray(x_scale) * jnp.asarray(w_scale)
    return acc.astype(jnp.float32) * scale


_reg("_contrib_quantized_fully_connected", _quantized_fully_connected,
     differentiable=False)


def _quantized_conv(qx, qw, kernel=None, stride=None, pad=None,
                    num_filter=0, layout="NHWC", x_scale=1.0, w_scale=1.0):
    """int8 conv with int32 accumulation (reference: quantized_conv.cc);
    NHWC/HWIO only (the TPU-native layout)."""
    nd = qx.ndim - 2
    stride = stride or (1,) * nd
    pad = pad or (0,) * nd
    acc = lax.conv_general_dilated(
        qx, qw, window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    scale = jnp.asarray(x_scale) * jnp.asarray(w_scale)
    return acc.astype(jnp.float32) * scale


_reg("_contrib_quantized_conv", _quantized_conv, differentiable=False)
