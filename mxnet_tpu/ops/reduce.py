"""Reduction ops.

TPU-native replacement of the reference's broadcast/reduce family
(reference: src/operator/tensor/broadcast_reduce_op_value.cc,
broadcast_reduce_op_index.cc, src/operator/tensor/broadcast_reduce-inl.h).
The reference hand-tiles reduction kernels; XLA maps these onto the VPU's
cross-lane reducers and fuses the producer, so each op is one jnp call.
Reference-specific semantics kept: ``exclude=True`` reduces over all axes
NOT listed (broadcast_reduce_op.h ReduceAxesParam), comparisons of argmax
dtype (reference returns float32 indices for nd API).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import _REGISTRY, Operator, alias


def _reg(name, fn, differentiable=True):
    _REGISTRY[name] = Operator(name, fn, differentiable=differentiable)


def _axes(axis, ndim, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _make_reduce(jfn):
    def impl(x, axis=None, keepdims=False, exclude=False):
        return jfn(x, axis=_axes(axis, x.ndim, exclude), keepdims=keepdims)
    return impl


for _n, _f in {"sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod,
               "max": jnp.max, "min": jnp.min, "nansum": jnp.nansum,
               "nanprod": jnp.nanprod}.items():
    _reg(_n, _make_reduce(_f))

alias("sum_axis", "sum")
alias("max_axis", "max")
alias("min_axis", "min")


def _norm(x, ord=2, axis=None, keepdims=False):
    ax = _axes(axis, x.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


_reg("norm", _norm)


def _make_argreduce(jfn):
    def impl(x, axis=None, keepdims=False):
        # reference nd.argmax returns float32 (src/operator/tensor/
        # broadcast_reduce_op_index.cc uses real_t output)
        return jfn(x, axis=axis, keepdims=keepdims).astype(jnp.float32)
    return impl


_reg("argmax", _make_argreduce(jnp.argmax), differentiable=False)
_reg("argmin", _make_argreduce(jnp.argmin), differentiable=False)


def _argmax_channel(x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


_reg("argmax_channel", _argmax_channel, differentiable=False)


def _moments(x, axes=None, keepdims=False):
    ax = _axes(axes, x.ndim)
    mean = jnp.mean(x, axis=ax, keepdims=keepdims)
    var = jnp.mean(jnp.square(x - jnp.mean(x, axis=ax, keepdims=True)),
                   axis=ax, keepdims=keepdims)
    return mean, var


_REGISTRY["moments"] = Operator("moments", _moments, nout=2)


def _cumsum(x, axis=None, dtype=None):
    return jnp.cumsum(x if dtype is None else x.astype(dtype), axis=axis)


_reg("cumsum", _cumsum)
_reg("logsumexp", lambda x, axis=None, keepdims=False:
     jnp.log(jnp.sum(jnp.exp(x - jnp.max(x, axis=axis, keepdims=True)),
                     axis=axis, keepdims=keepdims))
     + (jnp.max(x, axis=axis, keepdims=keepdims)))
