"""Neural-network ops.

TPU-native replacement of the reference's nn operator family
(reference: src/operator/nn/ — convolution.cc, fully_connected.cc,
pooling.cc, batch_norm.cc, layer_norm.cc, softmax.cc, dropout.cc,
activation.cc, upsampling.cc; src/operator/softmax_output.cc,
src/operator/rnn.cc, src/operator/nn/ctc_loss.cc).

Design: the reference dispatches each of these to cuDNN/MKLDNN/mshadow
hand kernels per device. Here each op is one XLA computation:
``lax.conv_general_dilated`` and ``lax.dot_general`` land on the MXU,
``lax.reduce_window`` handles pooling, and normalization/softmax chains are
left to XLA fusion (a single fused VPU pass — what the reference needed
separate cuDNN calls for). Layout is selectable like the reference's
(src/operator/nn/convolution.cc:395-507 supports NCHW/NHWC/...): the default
stays NCHW/OIHW for checkpoint parity, but ``layout='NHWC'`` keeps
activations channels-last end-to-end — measured ~2x faster for ResNet-50
training on TPU v5e (XLA's NCHW relayouting does not recover the gap).
Weight layout follows the reference rule: data layout with N->O, C->I
(NCHW -> OIHW weights, NHWC -> OHWI weights).
"""
from __future__ import annotations

import os as _os
from functools import partial

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import dtype_np
from .registry import _REGISTRY, Operator, alias


def _reg(name, fn, **kw):
    _REGISTRY[name] = Operator(name, fn, **kw)


def _tup(v, n):
    if v is None:
        return (1,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


# ------------------------------------------------------------- dense -------

def _fully_connected(*args, num_hidden=0, no_bias=False, flatten=True):
    x, w = args[0], args[1]
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    elif not flatten and x.ndim > 2:
        pass  # apply to last axis
    out = lax.dot_general(x, w, (((x.ndim - 1,), (1,)), ((), ())))
    if not no_bias and len(args) > 2:
        out = out + args[2]
    return out


_reg("FullyConnected", _fully_connected)
alias("fully_connected", "FullyConnected")


def _dot(a, b, transpose_a=False, transpose_b=False):
    # reference dot: contract last axis of a with first axis of b
    # (src/operator/tensor/dot-inl.h)
    if transpose_a:
        a = jnp.transpose(a)
    if transpose_b:
        b = jnp.transpose(b)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return lax.dot_general(a, b, (((a.ndim - 1,), (0,)), ((), ())))


_reg("dot", _dot)


def _batch_dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


_reg("batch_dot", _batch_dot)


# -------------------------------------------------------------- conv -------

def _conv_dims(kernel):
    return len(kernel)


_DEFAULT_LAYOUT = {1: "NCW", 2: "NCHW", 3: "NCDHW"}


def _data_layout(layout, nd):
    """Resolve an MXNet layout string ('NCHW', 'NHWC', 'NCW', 'NWC', ...)."""
    if not layout:
        return _DEFAULT_LAYOUT[nd]
    return layout


def _channel_axis(layout):
    return layout.index("C")


def _spatial_axes(layout):
    return [i for i, c in enumerate(layout) if c not in "NC"]


def _bias_shape(layout):
    shape = [1] * len(layout)
    shape[_channel_axis(layout)] = -1
    return tuple(shape)


def _convolution(*args, kernel=None, stride=None, dilate=None, pad=None,
                 num_filter=0, num_group=1, no_bias=False, layout=None,
                 workspace=None, cudnn_tune=None, cudnn_off=None):
    x, w = args[0], args[1]
    nd = _conv_dims(kernel) if kernel else x.ndim - 2
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad is not None else (0,) * nd
    lhs = _data_layout(layout, nd)
    # weight layout follows the data layout with N->O, C->I (reference rule:
    # NCHW data => OIHW weights, NHWC data => OHWI weights)
    rhs = lhs.replace("N", "O").replace("C", "I")
    out = lax.conv_general_dilated(
        x, w, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, feature_group_count=num_group,
        dimension_numbers=(lhs, rhs, lhs))
    if not no_bias and len(args) > 2:
        out = out + args[2].reshape(_bias_shape(lhs))
    return out


_reg("Convolution", _convolution)
alias("convolution", "Convolution")


def _deconvolution(*args, kernel=None, stride=None, dilate=None, pad=None,
                   adj=None, target_shape=None, num_filter=0, num_group=1,
                   no_bias=True, layout=None, workspace=None,
                   cudnn_tune=None, cudnn_off=None):
    x, w = args[0], args[1]
    nd = _conv_dims(kernel) if kernel else x.ndim - 2
    stride = _tup(stride, nd)
    dilate = _tup(dilate, nd)
    pad = _tup(pad, nd) if pad is not None else (0,) * nd
    adj = _tup(adj, nd) if adj is not None else (0,) * nd
    # transposed conv = gradient of conv w.r.t. input. weight layout in the
    # reference is the data layout with N->I, C->O: (in, out/group, kH, kW)
    # for NCHW, (in, kH, kW, out/group) for NHWC.
    lhs = _data_layout(layout, nd)
    rhs = lhs.replace("N", "I").replace("C", "O")
    w_sp = [rhs.index(c) for c in lhs if c not in "NC"]
    pads = []
    for i in range(nd):
        k = (w.shape[w_sp[i]] - 1) * dilate[i] + 1
        pads.append((k - 1 - pad[i], k - 1 - pad[i] + adj[i]))
    out = lax.conv_general_dilated(
        x, jnp.flip(w, axis=tuple(w_sp)),
        window_strides=(1,) * nd, padding=pads, lhs_dilation=stride,
        rhs_dilation=dilate, feature_group_count=num_group,
        dimension_numbers=(lhs, rhs, lhs))
    if not no_bias and len(args) > 2:
        out = out + args[2].reshape(_bias_shape(lhs))
    return out


_reg("Deconvolution", _deconvolution)


def _s2d_stem_conv(x, w, num_filter=0, no_bias=True, layout="NHWC"):
    """7x7/stride-2/pad-3 stem convolution computed as an equivalent
    4x4/stride-1 convolution over a 2x2 space-to-depth input.

    The MLPerf-ResNet TPU trick: a stride-2 conv with 3 input channels
    tiles the MXU poorly (the minor dim pads 3 -> 128 lanes); regrouping
    2x2 pixel phases into channels makes it a stride-1 conv with 4x the
    input channels over a 2x smaller spatial grid — numerically identical
    (tests/test_layout.py asserts exact agreement with Convolution).
    Derivation: out(i,j) = sum_{a,b} x[2i+a-3, 2j+b-3] w[a,b]; writing
    r = 2p+u splits taps by phase u=(a+1)%2 at offset p-i = (a-3-u)/2 in
    {-2..1}, i.e. a 4-tap stride-1 conv per phase with padding (2,1).
    Only used for NHWC; weight layout OHWI like Convolution.
    """
    n, h, ww_, c = x.shape
    o = w.shape[0]
    z = x.reshape(n, h // 2, 2, ww_ // 2, 2, c)
    z = jnp.transpose(z, (0, 1, 3, 2, 4, 5)).reshape(n, h // 2, ww_ // 2,
                                                     4 * c)
    whwio = jnp.transpose(w, (1, 2, 3, 0))          # (7,7,C,O)
    wp = jnp.pad(whwio, ((1, 0), (1, 0), (0, 0), (0, 0)))
    w2 = wp.reshape(4, 2, 4, 2, c, o)
    w2 = jnp.transpose(w2, (0, 2, 1, 3, 4, 5)).reshape(4, 4, 4 * c, o)
    return lax.conv_general_dilated(
        z, w2, window_strides=(1, 1), padding=((2, 1), (2, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


_reg("_s2d_stem_conv", _s2d_stem_conv)


# ------------------------------------------------------------ pooling ------

def _pool_pads(x, kernel, stride, pad, convention, sp_axes):
    pads = []
    for i, ax in enumerate(sp_axes):
        if convention == "full":
            # reference 'full' convention: ceil instead of floor
            # (src/operator/nn/pooling-inl.h)
            in_sz = x.shape[ax] + 2 * pad[i]
            out_sz = -(-(in_sz - kernel[i]) // stride[i]) + 1
            need = (out_sz - 1) * stride[i] + kernel[i] - x.shape[ax]
            pads.append((pad[i], max(need - pad[i], pad[i])))
        else:
            pads.append((pad[i], pad[i]))
    return pads


def _pooling(x, kernel=None, pool_type="max", global_pool=False, stride=None,
             pad=None, pooling_convention="valid", count_include_pad=True,
             layout=None, cudnn_off=None, p_value=None):
    nd = x.ndim - 2
    lay = _data_layout(layout, nd)
    sp_axes = _spatial_axes(lay)
    if global_pool:
        kernel = tuple(x.shape[a] for a in sp_axes)
        stride = (1,) * nd
        pad = (0,) * nd
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) if stride is not None else kernel if global_pool else _tup(stride, nd)
    pad = _tup(pad, nd) if pad is not None else (0,) * nd
    window = [1] * x.ndim
    strides = [1] * x.ndim
    pads = [(0, 0)] * x.ndim
    sp_pads = _pool_pads(x, kernel, stride, pad, pooling_convention, sp_axes)
    for i, ax in enumerate(sp_axes):
        window[ax] = kernel[i]
        strides[ax] = stride[i]
        pads[ax] = sp_pads[i]
    window, strides = tuple(window), tuple(strides)
    if pool_type == "max":
        # init must be a scalar literal: a traced/asarray init defeats
        # JAX's max-monoid recognition and reverse-mode AD of
        # reduce_window fails
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            int(jnp.iinfo(x.dtype).min)
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0 if jnp.issubdtype(x.dtype, jnp.floating)
                              else 0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / jnp.asarray(denom, x.dtype)
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    if pool_type == "lp":
        p = p_value or 2
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add,
                              window, strides, pads)
        return s ** (1.0 / p)
    raise ValueError(f"unknown pool_type {pool_type}")


_reg("Pooling", _pooling)
alias("pooling", "Pooling")


def _adaptive_avg_pool2d(x, output_size=1):
    os = _tup(output_size, 2)
    return jax.image.resize(
        jnp.mean(x, axis=(2, 3), keepdims=True), x.shape[:2] + os,
        method="nearest") if os == (1, 1) else _adaptive_pool_general(x, os)


def _adaptive_pool_general(x, os):
    b, c, h, w = x.shape
    oh, ow = os
    # exact when divisible; interpolated otherwise
    if h % oh == 0 and w % ow == 0:
        return jnp.mean(x.reshape(b, c, oh, h // oh, ow, w // ow), axis=(3, 5))
    return jax.image.resize(x, (b, c, oh, ow), method="linear")


_reg("_contrib_AdaptiveAvgPooling2D",
     lambda x, output_size=1: _adaptive_pool_general(x, _tup(output_size, 2)))


def _upsampling(*args, scale=1, sample_type="nearest", num_filter=0,
                multi_input_mode="concat", num_args=1, workspace=None):
    x = args[0]
    b, c, h, w = x.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    return jax.image.resize(x, (b, c, h * scale, w * scale), method="linear")


_reg("UpSampling", _upsampling)


def _bilinear_resize2d(x, height=None, width=None, scale_height=None,
                       scale_width=None, mode=None, align_corners=True):
    b, c, h, w = x.shape
    oh = height or int(h * scale_height)
    ow = width or int(w * scale_width)
    return jax.image.resize(x, (b, c, oh, ow), method="linear")


_reg("_contrib_BilinearResize2D", _bilinear_resize2d)


# ------------------------------------------------------- normalization -----

def _bn_reduce_axes(x, axis):
    return tuple(i for i in range(x.ndim) if i != axis)


def _bn_train_stats(x, axis):
    """fp32 E[x] and clamped E[x^2]-E[x]^2 as sibling reductions over one
    read of x (XLA emits one multi-output reduce fusion)."""
    red = _bn_reduce_axes(x, axis)
    xf = x.astype(jnp.float32)
    mean32 = jnp.mean(xf, axis=red)
    var32 = jnp.maximum(jnp.mean(xf * xf, axis=red) - mean32 * mean32, 0.0)
    return mean32, var32


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _bn_train_fused(axis, eps, x, gamma, beta):
    """Training-mode BN core with a hand-written backward.

    Autodiff of the stats+normalise chain produces a correct but
    reduction-heavy backward; the canonical BN gradient needs only two
    per-channel reductions — sum(dy) and sum(dy * xhat) — which are
    siblings over one joint read of (dy, x), followed by one fused
    elementwise pass for dx (reference computes the same grouping on GPU
    in src/operator/nn/batch_norm.cu DoBNBackward). Opt-in via
    MXNET_TPU_BN_FUSED_BWD=1; numerics pinned against the autodiff path
    in tests/test_bn_fused_bwd.py. Returns (out, batch_mean32, batch_var32)."""
    primal, _res = _bn_train_fused_fwd(axis, eps, x, gamma, beta)
    return primal


def _bn_train_fused_fwd(axis, eps, x, gamma, beta):
    mean32, var32 = _bn_train_stats(x, axis)
    inv32 = lax.rsqrt(var32 + eps)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    scale = inv32 * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean32 * scale
    out = (x * scale.astype(x.dtype).reshape(shape)
           + shift.astype(x.dtype).reshape(shape))
    return (out, mean32, var32), (x, gamma, beta, mean32, inv32)


def _bn_train_fused_bwd(axis, eps, res, cts):
    x, gamma, beta, mean32, inv32 = res
    dy, dmean_ct, dvar_ct = cts
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    red = _bn_reduce_axes(x, axis)
    n = _np.prod([x.shape[i] for i in red]).astype(_np.float32)
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xc = xf - mean32.reshape(shape)
    xhat = xc * inv32.reshape(shape)
    # the two reductions BN backward actually needs, siblings over one
    # joint (dy, x) read
    sum_dy = jnp.sum(dyf, axis=red)
    sum_dy_xhat = jnp.sum(dyf * xhat, axis=red)
    g32 = gamma.astype(jnp.float32)
    # dx for batch statistics: (g*inv) * (dy - mean(dy) - xhat*mean(dy*xhat))
    dx32 = (g32 * inv32).reshape(shape) * (
        dyf - (sum_dy / n).reshape(shape)
        - xhat * (sum_dy_xhat / n).reshape(shape))
    # cotangents on the returned batch mean/var (zero in normal training,
    # where they only feed non-differentiated running-stat updates)
    dx32 = (dx32 + (dmean_ct / n).reshape(shape)
            + (2.0 / n) * xc * dvar_ct.reshape(shape))
    return (dx32.astype(x.dtype), sum_dy_xhat.astype(gamma.dtype),
            sum_dy.astype(beta.dtype))


_bn_train_fused.defvjp(_bn_train_fused_fwd, _bn_train_fused_bwd)


def _batch_norm(*args, eps=1e-3, momentum=0.9, fix_gamma=True,
                use_global_stats=False, output_mean_var=False, axis=1,
                cudnn_off=None, _training=False):
    """Returns out, or (out, batch_mean, batch_var) when
    ``output_mean_var=True``. Running-stat update is done by the caller
    (gluon.nn.BatchNorm) — aux-state mutation can't live inside a pure op.
    Reference: src/operator/nn/batch_norm.cc (aux states moving_mean/var)."""
    x, gamma, beta, mmean, mvar = args
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    rs = lambda a: a.reshape(shape)  # noqa: E731
    if _training and not use_global_stats:
        if _os.environ.get("MXNET_TPU_BN_FUSED_BWD") == "1":
            out, mean32, var32 = _bn_train_fused(axis, eps, x, gamma, beta)
            mean, var = mean32.astype(x.dtype), var32.astype(x.dtype)
            if output_mean_var:
                return out, mean, var
            return out
        # Single-pass statistics: E[x] and E[x^2] are sibling reductions
        # over one read of x (XLA emits one multi-output reduce fusion),
        # halving the HBM traffic of the two-pass mean/centered-var form.
        # Accumulate in fp32 regardless of activation dtype.
        mean32, var32 = _bn_train_stats(x, axis)
        mean, var = mean32.astype(x.dtype), var32.astype(x.dtype)
    else:
        mean, var = mmean, mvar
        mean32 = mean.astype(jnp.float32)
        var32 = var.astype(jnp.float32)
    # Fold into out = x*scale + shift: one fused elementwise pass with no
    # (x - mean) intermediate; scale/shift are per-channel fp32 vectors.
    inv = lax.rsqrt(var32 + eps)
    scale = inv * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean32 * scale
    out = x * rs(scale.astype(x.dtype)) + rs(shift.astype(x.dtype))
    if output_mean_var:
        return out, mean, var
    return out


_REGISTRY["BatchNorm"] = Operator("BatchNorm", _batch_norm,
                                  needs_train=True)
alias("batch_norm", "BatchNorm")


def _layer_norm(x, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axis, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return out * gamma.reshape(shape) + beta.reshape(shape)


_reg("LayerNorm", _layer_norm)
alias("layer_norm", "LayerNorm")


def _group_norm(x, gamma, beta, num_groups=1, eps=1e-5,
                output_mean_var=False):
    b, c = x.shape[:2]
    g = num_groups
    xg = x.reshape((b, g, c // g) + x.shape[2:])
    red = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=red, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    return out * gamma.reshape(shape) + beta.reshape(shape)


_reg("GroupNorm", _group_norm)


def _instance_norm(x, gamma, beta, eps=1e-3):
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=red, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    return out * gamma.reshape(shape) + beta.reshape(shape)


_reg("InstanceNorm", _instance_norm)


def _l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        red = tuple(range(1, x.ndim))
    elif mode == "channel":
        red = (1,)
    else:  # spatial
        red = tuple(range(2, x.ndim))
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + eps)
    return x / n


_reg("L2Normalization", _l2_normalization)


def _lrn(x, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    sq = jnp.square(x)
    half = nsize // 2
    s = lax.reduce_window(sq, 0.0, lax.add,
                          (1, nsize, 1, 1), (1, 1, 1, 1),
                          [(0, 0), (half, half), (0, 0), (0, 0)])
    return x / jnp.power(knorm + alpha * s / nsize, beta)


_reg("LRN", _lrn)


# ------------------------------------------------------------ softmax ------

def _softmax(x, axis=-1, temperature=None, length=None, use_length=False,
             dtype=None):
    if temperature:
        x = x / temperature
    if use_length and length is not None:
        steps = jnp.arange(x.shape[axis])
        mask = steps[None, :] < length[:, None]
        x = jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - 2)) if
                      x.ndim > 2 else mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    return out.astype(dtype_np(dtype)) if dtype else out


_reg("softmax", _softmax)


def _log_softmax(x, axis=-1, temperature=None, dtype=None):
    if temperature:
        x = x / temperature
    out = jax.nn.log_softmax(x, axis=axis)
    return out.astype(dtype_np(dtype)) if dtype else out


_reg("log_softmax", _log_softmax)


def _softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


_reg("softmin", _softmin)


def _softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1],
                            dtype=data.dtype)
    return jnp.sum(-jnp.sum(onehot * logp, axis=-1))


_reg("softmax_cross_entropy", _softmax_cross_entropy)


@jax.custom_vjp
def _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore):
    return jax.nn.softmax(data, axis=-1)


def _so_fwd(data, label, grad_scale, ignore_label, use_ignore):
    out = jax.nn.softmax(data, axis=-1)
    return out, (out, label, grad_scale, ignore_label, use_ignore)


def _so_bwd(res, g):
    # Legacy semantics (reference: src/operator/softmax_output-inl.h):
    # backward ignores the incoming head grad and emits (p - onehot(label)).
    out, label, grad_scale, ignore_label, use_ignore = res
    onehot = jax.nn.one_hot(label.astype(jnp.int32), out.shape[-1],
                            dtype=out.dtype)
    grad = (out - onehot) * grad_scale
    if use_ignore:
        keep = (label != ignore_label).astype(out.dtype)
        grad = grad * keep[..., None]
    return grad, None, None, None, None


_softmax_output_core.defvjp(_so_fwd, _so_bwd)


def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    use_ignore=False, multi_output=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    flat = data.reshape(-1, data.shape[-1]) if data.ndim > 2 else data
    lab = label.reshape(-1) if label.ndim > 1 else label
    scale = grad_scale
    if normalization == "batch":
        scale = grad_scale / flat.shape[0]
    out = _softmax_output_core(flat, lab, scale, ignore_label, use_ignore)
    return out.reshape(data.shape)


_reg("SoftmaxOutput", _softmax_output)
alias("softmax_output", "SoftmaxOutput")


# --------------------------------------------------------- activation ------

def _activation(x, act_type="relu"):
    acts = {"relu": lambda v: jnp.maximum(v, 0),
            "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "softrelu": jax.nn.softplus, "softsign": jax.nn.soft_sign,
            "log_sigmoid": jax.nn.log_sigmoid,
            "gelu": lambda v: jax.nn.gelu(v, approximate=False),
            "silu": jax.nn.silu,
            "mish": lambda v: v * jnp.tanh(jax.nn.softplus(v))}
    return acts[act_type](x)


_reg("Activation", _activation)
alias("activation", "Activation")


def _leaky_relu(*args, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334, rng=None, _training=False):
    x = args[0]
    if act_type == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act_type == "prelu":
        gamma = args[1]
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else gamma
        return jnp.where(x > 0, x, g * x)
    if act_type == "elu":
        return jnp.where(x > 0, x, slope * (jnp.exp(x) - 1))
    if act_type == "selu":
        a, s = 1.6732632423543772, 1.0507009873554805
        return s * jnp.where(x > 0, x, a * (jnp.exp(x) - 1))
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "rrelu":
        if _training and rng is not None:
            u = jax.random.uniform(rng, x.shape, x.dtype, lower_bound,
                                   upper_bound)
        else:
            u = (lower_bound + upper_bound) / 2
        return jnp.where(x > 0, x, u * x)
    raise ValueError(f"unknown act_type {act_type}")


_REGISTRY["LeakyReLU"] = Operator("LeakyReLU", _leaky_relu, needs_rng=True,
                                  needs_train=True)


# ------------------------------------------------------------ dropout ------

def _dropout(x, rng=None, p=0.5, mode="training", axes=(), cudnn_off=None,
             _training=False):
    if p == 0 or (not _training and mode != "always"):
        return x
    shape = list(x.shape)
    for a in (axes or ()):
        shape[a] = 1
    keep = jax.random.bernoulli(rng, 1.0 - p, tuple(shape))
    return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))


_REGISTRY["Dropout"] = Operator("Dropout", _dropout, needs_rng=True,
                                needs_train=True)
alias("dropout", "Dropout")


# ---------------------------------------------------------- embedding ------

def _embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
               sparse_grad=False):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


_reg("Embedding", _embedding)
# reference: src/operator/contrib/sparse_embedding... (deprecated alias
# of Embedding with a row-sparse weight gradient); the invoke chokepoint
# gives it the sparse-grad tape path unconditionally
_reg("_contrib_SparseEmbedding",
     lambda data, weight, **kw: _embedding(data, weight,
                                           **{k: v for k, v in kw.items()
                                              if k != "sparse_grad"}))
alias("embedding", "Embedding")


# ---------------------------------------------------------------- ctc ------

def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False,
              blank_label="first"):
    """CTC loss via log-domain forward algorithm under lax.scan.

    Reference: src/operator/nn/ctc_loss.cc (warp-ctc). data: (T, B, A)
    pre-softmax activations; label: (B, L) padded with -1 (or 0 when
    blank_label='last'). Gradient comes from JAX AD through the scan —
    no hand-written backward as in warp-ctc.
    """
    # lengths arrive as params (not tensor inputs — symbol/register.py
    # declares only data/label), so they may still be NDArrays: unwrap.
    if data_lengths is not None:
        data_lengths = jnp.asarray(
            getattr(data_lengths, "_data", data_lengths)).astype(jnp.int32)
    if label_lengths is not None:
        label_lengths = jnp.asarray(
            getattr(label_lengths, "_data", label_lengths)).astype(jnp.int32)
    T, B, A = data.shape
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    blank = 0 if blank_label == "first" else A - 1
    lab = label.astype(jnp.int32)
    if blank_label == "last":
        lab = lab - 0  # labels already 0-based with blank at end
    L = lab.shape[1]
    pad_val = -1 if blank_label == "first" else blank
    if label_lengths is not None and use_label_lengths:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        lab_len = jnp.sum((lab != pad_val) & (lab != -1), axis=1).astype(jnp.int32)
    if data_lengths is not None and use_data_lengths:
        dat_len = data_lengths.astype(jnp.int32)
    else:
        dat_len = jnp.full((B,), T, jnp.int32)

    S = 2 * L + 1
    labels_safe = jnp.where(lab < 0, 0, lab)
    # extended label sequence: blank, l1, blank, l2, ...
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels_safe)
    ext_len = 2 * lab_len + 1

    neg_inf = jnp.asarray(-1e30, jnp.float32)
    pos = jnp.arange(S)
    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    alpha0 = jnp.where(pos[None, :] < 2,
                       jnp.take_along_axis(logp[0], ext, axis=1), neg_inf)
    alpha0 = jnp.where(pos[None, :] == 1, alpha0, jnp.where(pos[None, :] == 0,
                       alpha0, neg_inf))

    def step(alpha, lp_t):
        a_prev = alpha
        a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]],
                                   axis=1)
        a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]],
                                   axis=1)
        a_shift2 = jnp.where(same_as_prev2 | (pos[None, :] % 2 == 0),
                             neg_inf, a_shift2)
        m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
        m_safe = jnp.maximum(m, neg_inf)
        summed = (jnp.exp(a_prev - m_safe) + jnp.exp(a_shift1 - m_safe)
                  + jnp.exp(a_shift2 - m_safe))
        new = m_safe + jnp.log(summed) + jnp.take_along_axis(lp_t, ext, axis=1)
        return new, new

    _, alphas = lax.scan(step, alpha0, logp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T,B,S)

    # pick alpha at t = dat_len-1, s in {ext_len-1, ext_len-2}
    t_idx = (dat_len - 1)[:, None]
    alpha_T = jnp.take_along_axis(
        alphas.transpose(1, 0, 2), t_idx[..., None], axis=1)[:, 0]  # (B,S)
    end1 = jnp.take_along_axis(alpha_T, (ext_len - 1)[:, None], axis=1)[:, 0]
    end2 = jnp.take_along_axis(alpha_T,
                               jnp.maximum(ext_len - 2, 0)[:, None],
                               axis=1)[:, 0]
    m = jnp.maximum(end1, end2)
    ll = m + jnp.log(jnp.exp(end1 - m) + jnp.exp(end2 - m))
    return (-ll).astype(data.dtype)


_reg("CTCLoss", _ctc_loss)
alias("ctc_loss", "CTCLoss")


def _batch_norm_with_relu(*args, **kw):
    """reference: src/operator/contrib/batch_norm_relu.cc — BatchNorm
    with a fused ReLU epilogue (XLA fuses the max into the same
    elementwise pass)."""
    out = _batch_norm(*args, **kw)
    if isinstance(out, tuple):
        return (jnp.maximum(out[0], 0),) + out[1:]
    return jnp.maximum(out, 0)


_REGISTRY["_contrib_BatchNormWithReLU"] = Operator(
    "_contrib_BatchNormWithReLU", _batch_norm_with_relu,
    needs_train=True, nout=3)


def _sync_batch_norm(*args, eps=1e-3, momentum=0.9, fix_gamma=True,
                     use_global_stats=False, output_mean_var=False,
                     ndev=1, key=None, axis=1, axis_name=None,
                     _training=False, **kw):
    """reference: src/operator/contrib/sync_batch_norm.cc — BatchNorm
    whose batch statistics are averaged across data-parallel workers.
    TPU-native: inside shard_map/pmap pass ``axis_name`` and the
    moments are lax.pmean'd over that mesh axis (one fused ICI
    collective); the reference synchronised via its KVStore-side
    barrier+broadcast instead."""
    x, gamma, beta, mmean, mvar = args[:5]
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    rs = lambda a: a.reshape(shape)  # noqa: E731
    if _training and not use_global_stats:
        red = tuple(i for i in range(x.ndim) if i != axis)
        xf = x.astype(jnp.float32)
        mean32 = jnp.mean(xf, axis=red)
        meansq = jnp.mean(xf * xf, axis=red)
        if axis_name is not None:
            mean32 = lax.pmean(mean32, axis_name)
            meansq = lax.pmean(meansq, axis_name)
        var32 = jnp.maximum(meansq - mean32 * mean32, 0.0)
        mean, var = mean32.astype(x.dtype), var32.astype(x.dtype)
    else:
        mean, var = mmean, mvar
        mean32 = mean.astype(jnp.float32)
        var32 = var.astype(jnp.float32)
    inv = lax.rsqrt(var32 + eps)
    scale = inv * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean32 * scale
    out = x * rs(scale.astype(x.dtype)) + rs(shift.astype(x.dtype))
    if output_mean_var:
        return out, mean, var
    return out


_REGISTRY["_contrib_SyncBatchNorm"] = Operator(
    "_contrib_SyncBatchNorm", _sync_batch_norm, needs_train=True)


def _correlation(data1, data2, kernel_size=1, max_displacement=1,
                 stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """reference: src/operator/correlation.cc (FlowNet correlation
    layer). NCHW inputs; output channel d indexes the displacement grid
    (2*max_displacement/stride2+1)^2; each value is the patch
    correlation (mean over channels x kernel window) between data1 at
    (i,j) and data2 at (i+di, j+dj)."""
    n, c, h, w = data1.shape
    d = int(max_displacement)
    s2 = int(stride2)
    disps = list(range(-d, d + 1, s2))
    p = pad_size
    x1 = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    x2 = jnp.pad(data2, ((0, 0), (0, 0), (p + d, p + d), (p + d, p + d)))
    hh, ww = x1.shape[2], x1.shape[3]
    outs = []
    for di in disps:
        for dj in disps:
            shifted = lax.dynamic_slice(
                x2, (0, 0, d + di, d + dj), (n, c, hh, ww))
            prod = x1 * shifted if is_multiply else -jnp.abs(x1 - shifted)
            corr = jnp.mean(prod, axis=1)          # mean over channels
            if kernel_size > 1:
                k = int(kernel_size)
                corr = lax.reduce_window(
                    corr, 0.0, lax.add, (1, k, k), (1, 1, 1),
                    [(0, 0), (k // 2, k // 2), (k // 2, k // 2)]) / (k * k)
            outs.append(corr)
    out = jnp.stack(outs, axis=1)
    if stride1 > 1:
        out = out[:, :, ::int(stride1), ::int(stride1)]
    return out


_reg("Correlation", _correlation)
