"""Random sampling ops.

TPU-native replacement of the reference's sampler family
(reference: src/operator/random/sample_op.cc, multisample_op.cc,
shuffle_op.cc; RNG resource include/mxnet/random_generator.h). The
reference seeds per-device Philox/MT generators through the resource
manager; here every op draws a fresh fold of the global counter-based key
(mxnet_tpu._rng) — deterministic under mx.random.seed, parallel-safe, and
reproducible across devices.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import dtype_np
from .registry import _REGISTRY, Operator, alias


def _reg(name, fn, nout=1, differentiable=False):
    _REGISTRY[name] = Operator(name, fn, nout=nout, needs_rng=True,
                               differentiable=differentiable)


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _uniform(rng=None, low=0.0, high=1.0, shape=None, dtype="float32"):
    return jax.random.uniform(rng, _shape(shape), dtype_np(dtype), low, high)


def _normal(rng=None, loc=0.0, scale=1.0, shape=None, dtype="float32"):
    return loc + scale * jax.random.normal(rng, _shape(shape), dtype_np(dtype))


def _gamma(rng=None, alpha=1.0, beta=1.0, shape=None, dtype="float32"):
    return beta * jax.random.gamma(rng, alpha, _shape(shape), dtype_np(dtype))


def _exponential(rng=None, lam=1.0, shape=None, dtype="float32"):
    return jax.random.exponential(rng, _shape(shape), dtype_np(dtype)) / lam


def _poisson(rng=None, lam=1.0, shape=None, dtype="float32"):
    return jax.random.poisson(rng, lam, _shape(shape)).astype(dtype_np(dtype))


def _randint(rng=None, low=0, high=1, shape=None, dtype="int32"):
    return jax.random.randint(rng, _shape(shape), low, high,
                              dtype_np(dtype))


def _negative_binomial(rng=None, k=1, p=1.0, shape=None, dtype="float32"):
    lam = jax.random.gamma(rng, k, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(jax.random.fold_in(rng, 1), lam,
                              _shape(shape)).astype(dtype_np(dtype))


def _gen_negative_binomial(rng=None, mu=1.0, alpha=1.0, shape=None,
                           dtype="float32"):
    k = 1.0 / alpha
    p = k / (k + mu)
    lam = jax.random.gamma(rng, k, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(jax.random.fold_in(rng, 1), lam,
                              _shape(shape)).astype(dtype_np(dtype))


_reg("_random_uniform", _uniform)
_reg("_random_normal", _normal)
_reg("_random_gamma", _gamma)
_reg("_random_exponential", _exponential)
_reg("_random_poisson", _poisson)
_reg("_random_randint", _randint)
_reg("_random_negative_binomial", _negative_binomial)
_reg("_random_generalized_negative_binomial", _gen_negative_binomial)
alias("uniform", "_random_uniform")
alias("normal", "_random_normal")
alias("random_gamma", "_random_gamma")
alias("random_exponential", "_random_exponential")
alias("random_poisson", "_random_poisson")
alias("random_randint", "_random_randint")


# sample_* variants: per-element distribution parameters as array inputs
# (reference: src/operator/random/multisample_op.cc)

def _sample_uniform(low, high, rng=None, shape=None, dtype="float32"):
    s = _shape(shape)
    out_shape = low.shape + s
    u = jax.random.uniform(rng, out_shape, dtype_np(dtype))
    return low.reshape(low.shape + (1,) * len(s)) + u * (
        (high - low).reshape(low.shape + (1,) * len(s)))


def _sample_normal(mu, sigma, rng=None, shape=None, dtype="float32"):
    s = _shape(shape)
    n = jax.random.normal(rng, mu.shape + s, dtype_np(dtype))
    return (mu.reshape(mu.shape + (1,) * len(s))
            + n * sigma.reshape(sigma.shape + (1,) * len(s)))


def _sample_gamma(alpha, beta, rng=None, shape=None, dtype="float32"):
    s = _shape(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(rng, jnp.broadcast_to(a, alpha.shape + s),
                         dtype=dtype_np(dtype))
    return g * beta.reshape(beta.shape + (1,) * len(s))


_reg("_sample_uniform", _sample_uniform)
_reg("_sample_normal", _sample_normal)
_reg("_sample_gamma", _sample_gamma)
alias("sample_uniform", "_sample_uniform")
alias("sample_normal", "_sample_normal")
alias("sample_gamma", "_sample_gamma")


def _sample_multinomial(data, rng=None, shape=None, get_prob=False,
                        dtype="int32"):
    # data: (..., K) probabilities (reference: sample_multinomial_op.cc)
    s = _shape(shape) or ()
    n = 1
    for d in s:
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-30))
    flat = logits.reshape(-1, logits.shape[-1])
    draws = jax.random.categorical(rng, flat[:, None, :].repeat(max(n, 1), 1),
                                   axis=-1)
    out = draws.reshape(data.shape[:-1] + (s or ()))
    out = out.astype(dtype_np(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            flat, draws.reshape(flat.shape[0], -1), axis=1
        ).reshape(out.shape)
        return out, lp
    return out


_REGISTRY["_sample_multinomial"] = Operator(
    "_sample_multinomial", _sample_multinomial, nout=-1, needs_rng=True,
    differentiable=False)
alias("sample_multinomial", "_sample_multinomial")


def _shuffle(data, rng=None):
    return jax.random.permutation(rng, data, axis=0)


_reg("_shuffle", _shuffle)
alias("shuffle", "_shuffle")


def _bernoulli(rng=None, prob=0.5, shape=None, dtype="float32"):
    return jax.random.bernoulli(rng, prob, _shape(shape)).astype(
        dtype_np(dtype))


_reg("_sample_bernoulli", _bernoulli)
alias("bernoulli", "_sample_bernoulli")
