"""Elementwise unary / binary / scalar ops.

TPU-native replacement of the reference's elemwise op families
(reference: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_broadcast_op_*.cc, elemwise_binary_scalar_op_*.cc,
src/operator/mshadow_op.h). The reference hand-writes ~200 mshadow kernel
structs plus CUDA instantiations; here each op is one jax.numpy expression —
XLA fuses chains of them into single VPU loops, which is exactly what the
reference's NVRTC pointwise-fusion pass (src/operator/fusion/) tried to
recover at runtime.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias, _REGISTRY, Operator


def _reg(name, fn, differentiable=True):
    _REGISTRY[name] = Operator(name, fn, differentiable=differentiable)


# ----------------------------------------------------------------- unary ---

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "ceil": jnp.ceil, "floor": jnp.floor,
    "rint": jnp.rint, "round": jnp.round, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt, "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10,
    "log2": jnp.log2, "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "arcsin": jnp.arcsin,
    "arccos": jnp.arccos, "arctan": jnp.arctan, "sinh": jnp.sinh,
    "cosh": jnp.cosh, "tanh": jnp.tanh, "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "reciprocal": jnp.reciprocal, "negative": jnp.negative,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "gammaln": jax.scipy.special.gammaln,
    "identity": lambda x: x,
}
for _n, _f in _UNARY.items():
    _reg(_n, _f)

_reg("rsqrt", lambda x: lax.rsqrt(x))
_reg("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_reg("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_reg("logical_not", lambda x: jnp.logical_not(x).astype(x.dtype),
     differentiable=False)
_reg("relu", lambda x: jnp.maximum(x, 0))
_reg("sigmoid", jax.nn.sigmoid)
_reg("softsign", jax.nn.soft_sign)
_reg("hard_sigmoid", lambda x, alpha=0.2, beta=0.5:
     jnp.clip(alpha * x + beta, 0.0, 1.0))
_reg("softrelu", jax.nn.softplus)
_reg("gelu", jax.nn.gelu)
_reg("silu", jax.nn.silu)
_reg("log_sigmoid", jax.nn.log_sigmoid)
_reg("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
_reg("isnan", lambda x: jnp.isnan(x), differentiable=False)
_reg("isinf", lambda x: jnp.isinf(x), differentiable=False)
_reg("isfinite", lambda x: jnp.isfinite(x), differentiable=False)

alias("stop_gradient", "identity")
_reg("BlockGrad", lambda x: lax.stop_gradient(x))
alias("make_loss", "identity")

# ------------------------------------------------------- binary broadcast ---

_BINARY = {
    "broadcast_add": jnp.add, "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply, "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod, "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum, "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot, "arctan2": jnp.arctan2,
    "elemwise_add": jnp.add, "elemwise_sub": jnp.subtract,
    "elemwise_mul": jnp.multiply, "elemwise_div": jnp.divide,
}
for _n, _f in _BINARY.items():
    _reg(_n, _f)

alias("broadcast_plus", "broadcast_add")
alias("broadcast_minus", "broadcast_sub")
alias("maximum", "broadcast_maximum")
alias("minimum", "broadcast_minimum")
alias("hypot", "broadcast_hypot")

_CMP = {
    "broadcast_equal": jnp.equal, "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less, "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}
for _n, _f in _CMP.items():
    # comparisons return same-dtype 0/1 arrays in the reference nd API
    def _make(f):
        return lambda a, b: f(a, b).astype(jnp.result_type(a, b))
    _reg(_n, _make(_f), differentiable=False)

_reg("smooth_l1", lambda x, scalar=1.0: jnp.where(
    jnp.abs(x) < 1.0 / (scalar * scalar),
    0.5 * (scalar * x) ** 2, jnp.abs(x) - 0.5 / (scalar * scalar)))

# ----------------------------------------------------------- scalar forms ---
# Reference: src/operator/tensor/elemwise_binary_scalar_op_basic.cc (_plus_scalar …)

_SCALAR = {
    "_plus_scalar": lambda x, scalar: x + scalar,
    "_minus_scalar": lambda x, scalar: x - scalar,
    "_rminus_scalar": lambda x, scalar: scalar - x,
    "_mul_scalar": lambda x, scalar: x * scalar,
    "_div_scalar": lambda x, scalar: x / scalar,
    "_rdiv_scalar": lambda x, scalar: scalar / x,
    "_mod_scalar": lambda x, scalar: jnp.mod(x, scalar),
    "_rmod_scalar": lambda x, scalar: jnp.mod(scalar, x),
    "_power_scalar": lambda x, scalar: jnp.power(x, scalar),
    "_rpower_scalar": lambda x, scalar: jnp.power(scalar, x),
    "_maximum_scalar": lambda x, scalar: jnp.maximum(x, scalar),
    "_minimum_scalar": lambda x, scalar: jnp.minimum(x, scalar),
    "_hypot_scalar": lambda x, scalar: jnp.hypot(x, scalar),
}
for _n, _f in _SCALAR.items():
    _reg(_n, _f)

_SCALAR_CMP = {
    "_equal_scalar": jnp.equal, "_not_equal_scalar": jnp.not_equal,
    "_greater_scalar": jnp.greater, "_greater_equal_scalar": jnp.greater_equal,
    "_lesser_scalar": jnp.less, "_lesser_equal_scalar": jnp.less_equal,
}
for _n, _f in _SCALAR_CMP.items():
    def _make_s(f):
        return lambda x, scalar: f(x, scalar).astype(x.dtype)
    _reg(_n, _make_s(_f), differentiable=False)

_reg("where", lambda cond, x, y: jnp.where(cond.astype(bool), x, y))
_reg("zeros_like", jnp.zeros_like, differentiable=False)
_reg("ones_like", jnp.ones_like, differentiable=False)
