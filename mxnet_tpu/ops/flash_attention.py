"""Pallas flash attention for TPU — forward and backward kernels.

The reference framework has no fused attention kernel at all (SURVEY §5.7:
attention exists only as model-level example code), but the BERT-base
north-star config names "fused attention + AMP" — this module provides it
the TPU way: online-softmax (flash) kernels in Pallas that never
materialize the (T, T) score matrix in HBM, forward *and* backward.

Design (see /opt/skills/guides/pallas_guide.md):
- forward: grid = (B*H, Tq/bq, Tk/bk) with the key dimension innermost.
  Each program owns one query block; key/value blocks STREAM through VMEM
  via BlockSpec index maps (only one (bk, D) block resident at a time, so
  usable sequence length is not capped by K/V VMEM residency). Running
  max/denominator live in f32 scratch, which persists across the
  sequential TPU grid; the output block and the logsumexp row are written
  on the last key step. Matmuls hit the MXU with
  ``preferred_element_type=float32``.
- backward: two Pallas kernels recompute probabilities blockwise from the
  saved logsumexp (the standard flash backward):
    * dK/dV kernel, grid (B*H, Tk/bk, Tq/bq): owns one key block,
      streams query blocks, accumulates dK/dV (and the bias gradient) in
      f32 scratch using the transposed-score layout so the per-row
      logsumexp/delta enter as (1, bq) rows — no in-kernel transposes.
    * dQ kernel, grid (B*H, Tq/bq, Tk/bk): owns one query block, streams
      key blocks, accumulates dQ.
  Peak memory is O(T) end to end; tests pin both the gradients (vs
  ``jax.vjp`` of the XLA reference) and the O(T) memory scaling.
- causal masking skips the compute of fully-masked blocks (DMA still
  streams; a future refinement could prune the grid). Padding to block
  multiples is masked via the additive bias row (keys) and explicit
  position masks (queries) so padded rows contribute nothing to any
  gradient.
- off-TPU (CPU tests, virtual meshes) the same kernels run in interpret
  mode; ``attention_reference`` is the oracle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register

# Defaults tuned on TPU v5e (T=2048, D=64, causal fwd+bwd): small key
# blocks drown in per-grid-step overhead (128/128 ran 10x slower than
# 256/512); larger key blocks amortize it while staying well inside VMEM.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _prec(dtype):
    # fp32 inputs get true-fp32 MXU passes (3-pass emulation); bf16 inputs
    # run at native MXU rate. Accumulation is always f32 via
    # preferred_element_type.
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32 else None)


def _dot(a, b, dims, precision):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=precision)


def attention_reference(q, k, v, bias=None, causal=False, scale=None):
    """Plain XLA attention, numerically the oracle for the kernels.

    q/k/v: (B, H, T, D); bias: (B, Tk) additive (0 keep / -inf drop).
    """
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * s
    if bias is not None:
        logits = logits + bias[:, None, None, :].astype(jnp.float32)
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------- forward --


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, causal, scale, num_k):
    qi, ki = pl.program_id(1), pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: key blocks strictly above this query block's diagonal are
    # fully masked — skip their compute (their DMA still streams)
    run = (ki * block_k < (qi + 1) * block_q) if causal else True

    @pl.when(run)
    def _body():
        q, k_blk, v_blk = q_ref[...], k_ref[...], v_ref[...]
        prec = _prec(q.dtype)
        s = _dot(q, k_blk, ((1,), (1,)), prec) * scale  # (bq, bk) f32
        if bias_ref is not None:
            s = s + bias_ref[0, :][None, :]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + _dot(
            p.astype(v_blk.dtype) if v_blk.dtype != jnp.float32 else p,
            v_blk, ((1,), (0,)), prec)
        m_ref[...] = m_new

    @pl.when(ki == num_k - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[...] = m_ref[...] + jnp.log(l_safe)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _prepare(q, k, v, bias, block_q, block_k):
    """Pad to block multiples; return flattened operands + a bias row that
    always masks padded keys (None only when nothing needs masking)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    q = _pad_to(q, 2, block_q)
    k = _pad_to(k, 2, block_k)
    v = _pad_to(v, 2, block_k)
    Tq_p, Tk_p = q.shape[2], k.shape[2]
    if Tk_p != Tk or bias is not None:
        if bias is None:
            bias = jnp.zeros((B, Tk), jnp.float32)
        bias = jnp.pad(bias.astype(jnp.float32), ((0, 0), (0, Tk_p - Tk)),
                       constant_values=_NEG_INF)
    qf = q.reshape(B * H, Tq_p, D)
    kf = k.reshape(B * H, Tk_p, D)
    vf = v.reshape(B * H, Tk_p, D)
    return qf, kf, vf, bias, Tq_p, Tk_p


def _flash_forward(q, k, v, bias, causal, scale, block_q, block_k,
                   interpret, *, want_lse=False):
    B, H, Tq, D = q.shape
    s = scale if scale is not None else float(1.0 / (D ** 0.5))
    qf, kf, vf, bias_p, Tq_p, Tk_p = _prepare(q, k, v, bias, block_q,
                                              block_k)
    num_q, num_k = Tq_p // block_q, Tk_p // block_k
    grid = (B * H, num_q, num_k)

    in_specs = [
        pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if bias_p is not None:
        # (B, 1, Tk_p): the singleton sublane dim keeps the block shape
        # legal for TPU tiling (sublane must divide 8 or equal the array
        # dim)
        in_specs.append(pl.BlockSpec(
            (None, 1, block_k), lambda b, i, j: (b // H, 0, j),
            memory_space=pltpu.VMEM))
        args.append(bias_p[:, None, :])

        def kfn(qr, kr, vr, br, orf, lr, acc, m, l):
            _fwd_kernel(qr, kr, vr, br, orf, lr, acc, m, l, causal=causal,
                        scale=s, num_k=num_k)
    else:
        def kfn(qr, kr, vr, orf, lr, acc, m, l):
            _fwd_kernel(qr, kr, vr, None, orf, lr, acc, m, l,
                        causal=causal, scale=s, num_k=num_k)

    out, lse = pl.pallas_call(
        kfn,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    out = out.reshape(B, H, Tq_p, D)[:, :, :Tq, :]
    if want_lse:
        return out, lse
    return out


# --------------------------------------------------------------- backward --
#
# Both kernels work in the transposed-score layout sT = (k @ q^T) * scale
# + bias, shape (bk, bq): the per-query-row logsumexp and delta enter as
# (1, bq) rows and the per-key bias as a (bk, 1) column, so no in-kernel
# transposes are needed. p^T = exp(sT - lse); dS^T = p^T * (v @ dO^T -
# delta); then dV += p^T @ dO, dK += scale * dS^T @ q (key-block kernel)
# and dQ += scale * (dS^T)^T-contraction @ k (query-block kernel).


def _bwd_scores(q_ref, k_ref, bias_ref, lse_ref, *, scale, causal,
                qi, ki, tq_real):
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]
    q, k_blk = q_ref[...], k_ref[...]
    sT = _dot(k_blk, q, ((1,), (1,)), _prec(q.dtype)) * scale  # (bk, bq)
    if bias_ref is not None:
        sT = sT + bias_ref[...]                            # (bk, 1) column
    pT = jnp.exp(sT - lse_ref[0, :][None, :])              # (1, bq) row
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 1)
    valid = qpos < tq_real                 # padded query rows drop out
    if causal:
        kpos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0)
        valid = valid & (qpos >= kpos)
    return jnp.where(valid, pT, 0.0)


def _dkv_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref, bias_ref,
                dk_ref, dv_ref, dbias_ref, dk_acc, dv_acc, dbias_acc, *,
                causal, scale, num_q, tq_real):
    ki, qi = pl.program_id(1), pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)
        if dbias_acc is not None:
            dbias_acc[...] = jnp.zeros_like(dbias_acc)

    run = (ki * block_k < (qi + 1) * block_q) if causal else True

    @pl.when(run)
    def _body():
        pT = _bwd_scores(q_ref, k_ref, bias_ref, lse_ref, scale=scale,
                         causal=causal, qi=qi, ki=ki, tq_real=tq_real)
        do, v_blk, q = do_ref[...], v_ref[...], q_ref[...]
        dt, prec = q.dtype, _prec(q.dtype)
        lp = (lambda a: a) if dt == jnp.float32 else (lambda a:
                                                      a.astype(dt))
        dv_acc[...] += _dot(lp(pT), do, ((1,), (0,)), prec)  # (bk, D)
        dpT = _dot(v_blk, do, ((1,), (1,)), prec)            # (bk, bq)
        dsT = pT * (dpT - delta_ref[0, :][None, :])
        if dbias_acc is not None:
            dbias_acc[...] += jnp.sum(dsT, axis=1, keepdims=True)
        dk_acc[...] += scale * _dot(lp(dsT), q, ((1,), (0,)), prec)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)
        if dbias_ref is not None:
            dbias_ref[...] = dbias_acc[...]


def _dq_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref, bias_ref,
               dq_ref, dq_acc, *, causal, scale, num_k, tq_real):
    qi, ki = pl.program_id(1), pl.program_id(2)
    block_q, block_k = q_ref.shape[0], k_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = (ki * block_k < (qi + 1) * block_q) if causal else True

    @pl.when(run)
    def _body():
        pT = _bwd_scores(q_ref, k_ref, bias_ref, lse_ref, scale=scale,
                         causal=causal, qi=qi, ki=ki, tq_real=tq_real)
        do, v_blk, k_blk = do_ref[...], v_ref[...], k_ref[...]
        dt, prec = k_blk.dtype, _prec(k_blk.dtype)
        dpT = _dot(v_blk, do, ((1,), (1,)), prec)            # (bk, bq)
        dsT = pT * (dpT - delta_ref[0, :][None, :])
        if dt != jnp.float32:
            dsT = dsT.astype(dt)
        # contract the key dim of dsT (axis 0) with k: (bq, D)
        dq_acc[...] += scale * _dot(dsT, k_blk, ((0,), (0,)), prec)

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_backward(q, k, v, bias, out, lse, g, causal, scale, block_q,
                    block_k, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    # only compute the bias gradient when the caller actually passed a
    # bias; a bias row synthesized purely for key padding needs no grad
    want_dbias = bias is not None
    s = scale if scale is not None else float(1.0 / (D ** 0.5))
    qf, kf, vf, bias_p, Tq_p, Tk_p = _prepare(q, k, v, bias, block_q,
                                              block_k)
    gf = _pad_to(g, 2, block_q).reshape(B * H, Tq_p, D)
    of = _pad_to(out, 2, block_q).reshape(B * H, Tq_p, D)
    num_q, num_k = Tq_p // block_q, Tk_p // block_k

    # preprocess in plain XLA: delta = rowsum(dO * O); row layouts for the
    # kernels ((1, bq) rows, (bk, 1) bias column)
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)                               # (BH, Tq_p)
    delta_row = delta[:, None, :]                          # (BH, 1, Tq_p)
    lse_row = jnp.swapaxes(lse, 1, 2)                      # (BH, 1, Tq_p)
    bias_col = bias_p[:, :, None] if bias_p is not None else None

    def q_spec(fn):
        return pl.BlockSpec((None, block_q, D), fn, memory_space=pltpu.VMEM)

    def k_spec(fn):
        return pl.BlockSpec((None, block_k, D), fn, memory_space=pltpu.VMEM)

    def row_spec(fn):
        return pl.BlockSpec((None, 1, block_q), fn, memory_space=pltpu.VMEM)

    # ---- dK / dV (+ dbias): grid (BH, num_k, num_q), queries stream ----
    in_specs = [
        q_spec(lambda b, j, i: (b, i, 0)),
        q_spec(lambda b, j, i: (b, i, 0)),   # dO
        k_spec(lambda b, j, i: (b, j, 0)),
        k_spec(lambda b, j, i: (b, j, 0)),   # V
        row_spec(lambda b, j, i: (b, 0, i)),  # lse
        row_spec(lambda b, j, i: (b, 0, i)),  # delta
    ]
    args = [qf, gf, kf, vf, lse_row, delta_row]
    scratch = [pltpu.VMEM((block_k, D), jnp.float32),
               pltpu.VMEM((block_k, D), jnp.float32)]
    out_specs = [k_spec(lambda b, j, i: (b, j, 0)),
                 k_spec(lambda b, j, i: (b, j, 0))]
    out_shape = [jax.ShapeDtypeStruct((B * H, Tk_p, D), q.dtype),
                 jax.ShapeDtypeStruct((B * H, Tk_p, D), q.dtype)]
    if bias_p is not None:
        in_specs.append(pl.BlockSpec((None, block_k, 1),
                                     lambda b, j, i: (b // H, j, 0),
                                     memory_space=pltpu.VMEM))
        args.append(bias_col)
    if want_dbias:
        scratch.append(pltpu.VMEM((block_k, 1), jnp.float32))
        out_specs.append(pl.BlockSpec((None, block_k, 1),
                                      lambda b, j, i: (b, j, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((B * H, Tk_p, 1), jnp.float32))

        def dkv(qr, dor, kr, vr, lr, dr, br, dkr, dvr, dbr, dka, dva, dba):
            _dkv_kernel(qr, dor, kr, vr, lr, dr, br, dkr, dvr, dbr,
                        dka, dva, dba, causal=causal, scale=s,
                        num_q=num_q, tq_real=Tq)
    elif bias_p is not None:
        # bias row needed to recompute probabilities (key padding), but
        # its gradient is not
        def dkv(qr, dor, kr, vr, lr, dr, br, dkr, dvr, dka, dva):
            _dkv_kernel(qr, dor, kr, vr, lr, dr, br, dkr, dvr, None,
                        dka, dva, None, causal=causal, scale=s,
                        num_q=num_q, tq_real=Tq)
    else:
        def dkv(qr, dor, kr, vr, lr, dr, dkr, dvr, dka, dva):
            _dkv_kernel(qr, dor, kr, vr, lr, dr, None, dkr, dvr, None,
                        dka, dva, None, causal=causal, scale=s,
                        num_q=num_q, tq_real=Tq)

    res = pl.pallas_call(
        dkv, grid=(B * H, num_k, num_q), in_specs=in_specs,
        out_specs=out_specs, out_shape=out_shape,
        scratch_shapes=scratch, interpret=interpret)(*args)
    dk, dv = res[0], res[1]
    dbias = None
    if want_dbias:
        # per-(b,h,k) bias grads -> sum heads, drop key padding
        dbias = res[2].reshape(B, H, Tk_p)[:, :, :Tk].sum(axis=1)
        dbias = dbias.astype(bias.dtype)

    # ---- dQ: grid (BH, num_q, num_k), keys stream ----------------------
    in_specs = [
        q_spec(lambda b, i, j: (b, i, 0)),
        q_spec(lambda b, i, j: (b, i, 0)),   # dO
        k_spec(lambda b, i, j: (b, j, 0)),
        k_spec(lambda b, i, j: (b, j, 0)),   # V
        row_spec(lambda b, i, j: (b, 0, i)),  # lse
        row_spec(lambda b, i, j: (b, 0, i)),  # delta
    ]
    args = [qf, gf, kf, vf, lse_row, delta_row]
    if bias_p is not None:
        in_specs.append(pl.BlockSpec((None, block_k, 1),
                                     lambda b, i, j: (b // H, j, 0),
                                     memory_space=pltpu.VMEM))
        args.append(bias_col)

        def dqk(qr, dor, kr, vr, lr, dr, br, dqr, dqa):
            _dq_kernel(qr, dor, kr, vr, lr, dr, br, dqr, dqa,
                       causal=causal, scale=s, num_k=num_k, tq_real=Tq)
    else:
        def dqk(qr, dor, kr, vr, lr, dr, dqr, dqa):
            _dq_kernel(qr, dor, kr, vr, lr, dr, None, dqr, dqa,
                       causal=causal, scale=s, num_k=num_k, tq_real=Tq)

    dq = pl.pallas_call(
        dqk, grid=(B * H, num_q, num_k), in_specs=in_specs,
        out_specs=q_spec(lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret)(*args)

    dq = dq.reshape(B, H, Tq_p, D)[:, :, :Tq, :]
    dk = dk.reshape(B, H, Tk_p, D)[:, :, :Tk, :]
    dv = dv.reshape(B, H, Tk_p, D)[:, :, :Tk, :]
    return dq, dk, dv, dbias


def _on_tpu():
    try:
        return jax.default_backend() == "tpu" or \
            jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, None, causal, scale, block_q, block_k,
                          interpret)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, None, causal, scale, block_q,
                              block_k, interpret, want_lse=True)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    dq, dk, dv, _ = _flash_backward(q, k, v, None, out, lse, g, causal,
                                    scale, block_q, block_k, interpret)
    return dq, dk, dv


_flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_bias(q, k, v, bias, causal, scale, block_q, block_k,
                          interpret):
    return _flash_forward(q, k, v, bias, causal, scale, block_q, block_k,
                          interpret)


def _fab_fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, bias, causal, scale, block_q,
                              block_k, interpret, want_lse=True)
    return out, (q, k, v, bias, out, lse)


def _fab_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, bias, out, lse = res
    dq, dk, dv, dbias = _flash_backward(q, k, v, bias, out, lse, g,
                                        causal, scale, block_q, block_k,
                                        interpret)
    return dq, dk, dv, dbias


_flash_attention_bias.defvjp(_fab_fwd, _fab_bwd)


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Flash attention entry point. q/k/v: (B, H, T, D); bias: (B, Tk)
    additive row (0 = keep, large-negative = drop)."""
    if interpret is None:
        interpret = not _on_tpu()
    block_q = min(block_q, max(q.shape[2], 8))
    block_k = min(block_k, max(k.shape[2], 8))
    if bias is None:
        return _flash_attention(q, k, v, causal, scale, block_q, block_k,
                                interpret)
    return _flash_attention_bias(q, k, v, bias, causal, scale, block_q,
                                 block_k, interpret)


@register("scaled_dot_product_attention")
def _sdpa_op(q, k, v, bias=None, *, causal=False, scale=None,
             flash=True):
    """Registered attention op: flash kernel on TPU, interpret/XLA
    reference elsewhere. Inputs (B, H, T, D)."""
    if not flash:
        return attention_reference(q, k, v, bias, causal, scale)
    return flash_attention(q, k, v, bias=bias, causal=causal, scale=scale)
