"""Pallas flash attention for TPU.

The reference framework has no fused attention kernel at all (SURVEY §5.7:
attention exists only as model-level example code), but the BERT-base
north-star config names "fused attention + AMP" — this module provides it
the TPU way: an online-softmax (flash) kernel in Pallas that never
materializes the (T, T) score matrix in HBM.

Design (see /opt/skills/guides/pallas_guide.md):
- grid = (B*H, T/BLOCK_Q); each program owns one query block in VMEM and
  streams key/value blocks, maintaining running max/denominator (the
  standard flash recurrence) in f32 scratch. Matmuls hit the MXU with
  ``preferred_element_type=float32``.
- causal masking skips fully-masked key blocks; padding is handled with an
  optional additive bias row (B, T) loaded per key block.
- backward: ``jax.custom_vjp`` recomputes attention blockwise with the
  lax reference implementation and differentiates that — O(T) memory
  forward, standard-precision backward. (A hand-written Pallas backward is
  a further optimization, not a semantic change.)
- off-TPU (CPU tests, virtual meshes) the same kernel runs in interpret
  mode; ``attention_reference`` is the oracle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def attention_reference(q, k, v, bias=None, causal=False, scale=None):
    """Plain XLA attention, numerically the oracle for the kernel.

    q/k/v: (B, H, T, D); bias: (B, Tk) additive (0 keep / -inf drop).
    """
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * s
    if bias is not None:
        logits = logits + bias[:, None, None, :].astype(jnp.float32)
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, causal, scale, block_k,
                  seq_k):
    qi = pl.program_id(1)
    block_q = q_ref.shape[0]

    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[...].astype(jnp.float32) * scale
    num_k = pl.cdiv(seq_k, block_k)

    def body(ki, _):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(ki * block_k, block_k)][None, :]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        return 0

    if causal:
        # skip key blocks strictly above the diagonal of this query block
        last = jnp.minimum(
            pl.cdiv((qi + 1) * block_q, block_k), num_k)
        jax.lax.fori_loop(0, last, body, 0)
    else:
        jax.lax.fori_loop(0, num_k, body, 0)

    o_ref[...] = (acc_ref[...] /
                  jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x, size
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), size


def _flash_forward(q, k, v, bias, causal, scale, block_q, block_k,
                   interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    s = scale if scale is not None else float(1.0 / (D ** 0.5))

    q, _ = _pad_to(q, 2, block_q)
    k, _ = _pad_to(k, 2, block_k)
    v, _ = _pad_to(v, 2, block_k)
    Tq_p, Tk_p = q.shape[2], k.shape[2]
    # padded keys must never receive weight: extend the bias row
    if Tk_p != Tk or bias is not None:
        if bias is None:
            bias = jnp.zeros((B, Tk), q.dtype)
        bias = jnp.pad(bias.astype(jnp.float32), ((0, 0), (0, Tk_p - Tk)),
                       constant_values=_NEG_INF)

    qf = q.reshape(B * H, Tq_p, D)
    kf = k.reshape(B * H, Tk_p, D)
    vf = v.reshape(B * H, Tk_p, D)

    grid = (B * H, Tq_p // block_q)
    in_specs = [
        pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, Tk_p, D), lambda b, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((None, Tk_p, D), lambda b, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if bias is not None:
        # one bias row per batch entry, shared across its H heads
        in_specs.append(pl.BlockSpec(
            (1, Tk_p), lambda b, i: (b // H, 0),
            memory_space=pltpu.VMEM))
        args.append(bias)

        def kfn(qr, kr, vr, br, orf, acc, m, l):
            _flash_kernel(qr, kr, vr, br, orf, acc, m, l, causal=causal,
                          scale=s, block_k=block_k, seq_k=Tk_p)
    else:
        def kfn(qr, kr, vr, orf, acc, m, l):
            _flash_kernel(qr, kr, vr, None, orf, acc, m, l, causal=causal,
                          scale=s, block_k=block_k, seq_k=Tk_p)

    out = pl.pallas_call(
        kfn,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq_p, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, Tq_p, D)[:, :, :Tq, :]


def _on_tpu():
    try:
        return jax.default_backend() == "tpu" or \
            jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, None, causal, scale, block_q, block_k,
                          interpret)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, None, causal, scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: attention_reference(q, k, v, None, causal, scale),
        q, k, v)
    return vjp(g)


_flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_bias(q, k, v, bias, causal, scale, block_q, block_k,
                          interpret):
    return _flash_forward(q, k, v, bias, causal, scale, block_q, block_k,
                          interpret)


def _fab_fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, bias, causal, scale, block_q, block_k,
                         interpret)
    return out, (q, k, v, bias)


def _fab_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, bias = res
    _, vjp = jax.vjp(
        lambda q, k, v, b: attention_reference(q, k, v, b, causal, scale),
        q, k, v, bias)
    return vjp(g)


_flash_attention_bias.defvjp(_fab_fwd, _fab_bwd)


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Flash attention entry point. q/k/v: (B, H, T, D); bias: (B, Tk)
    additive row (0 = keep, large-negative = drop)."""
    if interpret is None:
        interpret = not _on_tpu()
    block_q = min(block_q, max(q.shape[2], 8))
    block_k = min(block_k, max(k.shape[2], 8))
    if bias is None:
        return _flash_attention(q, k, v, causal, scale, block_q, block_k,
                                interpret)
    return _flash_attention_bias(q, k, v, bias, causal, scale, block_q,
                                 block_k, interpret)


@register("scaled_dot_product_attention")
def _sdpa_op(q, k, v, bias=None, *, causal=False, scale=None,
             flash=True):
    """Registered attention op: flash kernel on TPU, interpret/XLA
    reference elsewhere. Inputs (B, H, T, D)."""
    if not flash:
        return attention_reference(q, k, v, bias, causal, scale)
    return flash_attention(q, k, v, bias=bias, causal=causal, scale=scale)
