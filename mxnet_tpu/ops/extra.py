"""Coverage ops: the long tail of reference-registered operators.

Each section names its reference provenance. These are the ops the
OPS_LEDGER flagged absent that have clean XLA expressions: internal
comparison/logical names (backing NDArray operators), legacy output
layers (src/operator/regression_output.cc, svm_output.cc,
softmax_activation.cc), the spatial-transformer family
(src/operator/spatial_transformer.cc, bilinear_sampler.cc,
grid_generator.cc, roi_pooling.cc, crop.cc), im2col/col2im
(src/operator/nn/im2col.h), extra samplers (src/operator/random/),
multi-tensor + FTML/AdamW/LAMB-mp optimizer kernels
(src/operator/optimizer_op.cc, contrib/adamw.cc), and small contrib ops
(quadratic, allclose, arange_like, index ops, box encode/decode, fft).
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import _REGISTRY, Operator, alias, register


def _reg(name, fn, **kw):
    _REGISTRY[name] = Operator(name, fn, **kw)


# ------------------------------------------------- internal elemwise names --
# (reference: src/operator/tensor/elemwise_binary_broadcast_op_logic.cc and
# ndarray.py operator dispatch; the underscored names back __eq__ etc.)

for _name, _f in [
    ("_equal", lambda a, b: (a == b)),
    ("_not_equal", lambda a, b: (a != b)),
    ("_greater", lambda a, b: (a > b)),
    ("_greater_equal", lambda a, b: (a >= b)),
    ("_lesser", lambda a, b: (a < b)),
    ("_lesser_equal", lambda a, b: (a <= b)),
    ("_logical_and", lambda a, b: jnp.logical_and(a, b)),
    ("_logical_or", lambda a, b: jnp.logical_or(a, b)),
    ("_logical_xor", lambda a, b: jnp.logical_xor(a, b)),
]:
    _reg(_name, (lambda f: lambda a, b: f(a, b).astype(a.dtype))(_f),
         differentiable=False)

for _name, _f in [
    ("_logical_and_scalar", lambda a, s: jnp.logical_and(a, s != 0)),
    ("_logical_or_scalar", lambda a, s: jnp.logical_or(a, s != 0)),
    ("_logical_xor_scalar", lambda a, s: jnp.logical_xor(a != 0, s != 0)),
]:
    _reg(_name,
         (lambda f: lambda a, scalar=0.0: f(a, scalar).astype(a.dtype))(_f),
         differentiable=False)

_reg("_mod", lambda a, b: jnp.mod(a, b))
_reg("_power", lambda a, b: jnp.power(a, b))
_reg("_grad_add", lambda a, b: a + b)
_reg("add_n", lambda arrays: sum(arrays[1:], arrays[0]), variadic=True)
alias("ElementWiseSum", "add_n")
_reg("digamma", lambda x: jax.scipy.special.digamma(x))
_reg("_histogram", lambda data, bin_cnt=10, range=None, **_:
     jnp.histogram(data, bins=int(bin_cnt),
                   range=range)[0], differentiable=False)
_reg("_linspace", lambda start=0.0, stop=1.0, num=50, endpoint=True,
     dtype="float32", **_: jnp.linspace(start, stop, int(num),
                                        endpoint=endpoint),
     differentiable=False)
_reg("_square_sum", lambda x, axis=None, keepdims=False:
     jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


def _split_v2(x, indices=(), axis=0, squeeze_axis=False, sections=0):
    """reference: src/operator/tensor/matrix_op.cc _split_v2."""
    if sections and sections > 0:
        parts = jnp.split(x, sections, axis=axis)
    else:
        parts = jnp.split(x, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


_reg("_split_v2", _split_v2, nout=2)


def _unravel_index(indices, shape=None):
    out = jnp.stack(jnp.unravel_index(indices.astype(jnp.int32), shape))
    return out.astype(indices.dtype)


_reg("_unravel_index", _unravel_index, differentiable=False)


def _ravel_multi_index(data, shape=None):
    idx = tuple(data[i].astype(jnp.int32) for i in range(data.shape[0]))
    return jnp.ravel_multi_index(idx, shape, mode="clip").astype(data.dtype)


_reg("_ravel_multi_index", _ravel_multi_index, differentiable=False)


def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """reference: _slice_assign (matrix_op.cc) — functional here: returns
    the updated copy (immutability by design)."""
    idx = tuple(slice(b if b is not None else None,
                      e if e is not None else None,
                      s if s else None)
                for b, e, s in zip(begin, end,
                                   step or (None,) * len(begin)))
    return lhs.at[idx].set(rhs)


_reg("_slice_assign", _slice_assign)
_reg("_slice_assign_scalar",
     lambda lhs, scalar=0.0, begin=(), end=(), step=():
     _slice_assign(lhs, scalar, begin, end, step))


def _im2col(data, kernel=None, stride=None, dilate=None, pad=None):
    """reference: src/operator/nn/im2col.h via lax patch extraction.
    data (N, C, H, W) -> (N, C*kh*kw, L)."""
    nd_ = len(kernel)
    stride = stride or (1,) * nd_
    dilate = dilate or (1,) * nd_
    pad = pad or (0,) * nd_
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=tuple(kernel), window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate))
    n = data.shape[0]
    return patches.reshape(n, patches.shape[1], -1)


_reg("im2col", _im2col)


def _col2im(data, output_size=None, kernel=None, stride=None, dilate=None,
            pad=None):
    """Adjoint of im2col (reference: col2im in im2col.h): scatter-add
    columns back — expressed as the vjp of the patch extraction."""
    n, _, _ = data.shape
    c = data.shape[1] // int(_np.prod(kernel))
    out_shape = (n, c) + tuple(output_size)
    primal = jnp.zeros(out_shape, data.dtype)
    _, vjp = jax.vjp(
        lambda x: _im2col(x, kernel=kernel, stride=stride, dilate=dilate,
                          pad=pad), primal)
    return vjp(data)[0]


_reg("col2im", _col2im)


def _all_finite(data, init_output=True):
    return jnp.isfinite(data).all()[None].astype(jnp.float32)


_reg("all_finite", _all_finite, differentiable=False)
_reg("multi_all_finite",
     lambda arrays, num_arrays=1, init_output=True:
     jnp.stack([jnp.isfinite(a).all() for a in arrays]).all()[None]
     .astype(jnp.float32),
     variadic=True, differentiable=False)
_reg("multi_sum_sq",
     lambda arrays, num_arrays=1:
     tuple(jnp.sum(jnp.square(a))[None] for a in arrays),
     variadic=True, nout=2, differentiable=False)
_reg("reset_arrays",
     lambda arrays, num_arrays=1: tuple(jnp.zeros_like(a) for a in arrays),
     variadic=True, nout=2, differentiable=False)


# --------------------------------------------------- legacy output layers --
# reference: src/operator/regression_output.cc, svm_output.cc,
# softmax_activation.cc. Like SoftmaxOutput, the backward ignores the head
# gradient and emits (pred - label)-style gradients.

def _make_output_op(name, fwd, bwd_fn):
    @jax.custom_vjp
    def core(data, label, grad_scale):
        return fwd(data)

    def core_fwd(data, label, grad_scale):
        out = fwd(data)
        return out, (out, label, grad_scale)

    def core_bwd(res, g):
        out, label, grad_scale = res
        return bwd_fn(out, label) * grad_scale, None, None

    core.defvjp(core_fwd, core_bwd)
    _reg(name, lambda data, label, grad_scale=1.0:
         core(data, label, grad_scale))


_make_output_op("LinearRegressionOutput", lambda x: x,
                lambda out, lab: (out - lab.reshape(out.shape)) /
                _np.float32(1.0))
_make_output_op("LogisticRegressionOutput", jax.nn.sigmoid,
                lambda out, lab: out - lab.reshape(out.shape))
_make_output_op("MAERegressionOutput", lambda x: x,
                lambda out, lab: jnp.sign(out - lab.reshape(out.shape)))


def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    """Forward is identity (reference: svm_output.cc); backward applies
    the hinge subgradient."""
    @jax.custom_vjp
    def core(data, label):
        return data

    def core_fwd(data, label):
        return data, (data, label)

    def core_bwd(res, g):
        d, lab = res
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), d.shape[-1],
                                dtype=d.dtype)
        score_true = jnp.sum(d * onehot, axis=-1, keepdims=True)
        if use_linear:  # L1-SVM subgradient
            viol = ((d - score_true + margin) > 0).astype(d.dtype)
            viol = viol * (1 - onehot)
            grad = viol - onehot * jnp.sum(viol, -1, keepdims=True)
        else:  # L2-SVM
            viol = jnp.maximum(d - score_true + margin, 0.0) * (1 - onehot)
            grad = 2 * viol - onehot * jnp.sum(2 * viol, -1, keepdims=True)
        return grad * regularization_coefficient, None

    core.defvjp(core_fwd, core_bwd)
    return core(data, label)


_reg("SVMOutput", _svm_output)
_reg("SoftmaxActivation",
     lambda data, mode="instance":
     jax.nn.softmax(data, axis=-1 if mode == "instance" else 1))
alias("MakeLoss", "make_loss")
alias("BatchNorm_v1", "BatchNorm")
alias("Convolution_v1", "Convolution")
alias("Pooling_v1", "Pooling")


# ------------------------------------------- spatial transformer family ----

def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """reference: src/operator/grid_generator.cc. affine: data (N, 6)
    transform -> sampling grid (N, 2, H, W) of [-1, 1] (x, y) coords."""
    h, w = target_shape
    if transform_type == "affine":
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, HW)
        # tiny matmul: full precision costs nothing and keeps the grid
        # exact on TPU (default bf16 einsum visibly warps samples)
        out = jnp.einsum("nij,jk->nik", theta, base,
                         precision=jax.lax.Precision.HIGHEST)     # (N,2,HW)
        return out.reshape(n, 2, h, w)
    # 'warp': data (N, 2, H, W) flow field in pixels -> normalized coords
    n, _, hh, ww = data.shape
    ys = jnp.arange(hh, dtype=data.dtype)
    xs = jnp.arange(ww, dtype=data.dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    x = (data[:, 0] + gx) * 2.0 / max(ww - 1, 1) - 1.0
    y = (data[:, 1] + gy) * 2.0 / max(hh - 1, 1) - 1.0
    return jnp.stack([x, y], axis=1)


_reg("GridGenerator", _grid_generator)


def _bilinear_sampler(data, grid, cudnn_off=None):
    """reference: src/operator/bilinear_sampler.cc. data (N, C, H, W),
    grid (N, 2, Ho, Wo) with (x, y) in [-1, 1]; zero padding outside."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0          # (N, Ho, Wo)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yy, xx):
        inb = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) &
               (xx <= w - 1))                         # (N, Ho, Wo)
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        vals = jax.vmap(lambda img, y_, x_: img[:, y_, x_])(
            data, yc, xc)                             # (N, C, Ho, Wo)
        return vals * inb[:, None].astype(data.dtype)

    out = ((1 - wy) * (1 - wx))[:, None] * gather(y0, x0) + \
        ((1 - wy) * wx)[:, None] * gather(y0, x0 + 1) + \
        (wy * (1 - wx))[:, None] * gather(y0 + 1, x0) + \
        (wy * wx)[:, None] * gather(y0 + 1, x0 + 1)
    return out


_reg("BilinearSampler", _bilinear_sampler)


def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=None):
    """reference: src/operator/spatial_transformer.cc: GridGenerator +
    BilinearSampler fused."""
    grid = _grid_generator(loc, transform_type, target_shape)
    return _bilinear_sampler(data, grid)


_reg("SpatialTransformer", _spatial_transformer)


def _roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """reference: src/operator/roi_pooling.cc (Fast R-CNN max pooling).
    TPU deviation: bins are max-pooled over a fixed 4x4 sampling grid per
    bin instead of the exact (data-dependent) integer bin extents, which
    cannot be traced with static shapes."""
    ph, pw = pooled_size
    sr = 4
    n, c, h, w = data.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        bw = jnp.maximum(x2 - x1 + 1, 1.0) / pw
        bh = jnp.maximum(y2 - y1 + 1, 1.0) / ph
        gy = y1 + (jnp.arange(ph * sr) + 0.5) * bh / sr
        gx = x1 + (jnp.arange(pw * sr) + 0.5) * bw / sr
        yc = jnp.clip(gy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(gx, 0, w - 1).astype(jnp.int32)
        img = data[b]                                 # (C, H, W)
        samples = img[:, yc[:, None], xc[None, :]]    # (C, PH*sr, PW*sr)
        return samples.reshape(c, ph, sr, pw, sr).max(axis=(2, 4))

    return jax.vmap(one)(rois)


_reg("ROIPooling", _roi_pooling)


def _crop(args, offset=(0, 0), h_w=(0, 0), center_crop=False,
          num_args=1):
    """reference: src/operator/crop.cc. Crop data (N, C, H, W) to h_w (or
    to the second input's spatial size). args: [data] or [data, like]."""
    data = args[0]
    if len(args) > 1:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = h_w
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = offset
    return data[:, :, oy:oy + th, ox:ox + tw]


_reg("Crop", _crop, variadic=True)


# ----------------------------------------------------------- samplers ------
# reference: src/operator/random/sample_multinomial_op.cc etc. The
# _sample_* family draws one row of samples per distribution-parameter row.

def _sample_exponential(lam, shape=(), dtype="float32", rng=None):
    sh = tuple(lam.shape) + (tuple(shape) if shape else ())
    return jax.random.exponential(rng, sh) / lam.reshape(
        lam.shape + (1,) * (len(sh) - lam.ndim))


_REGISTRY["_sample_exponential"] = Operator(
    "_sample_exponential", _sample_exponential, needs_rng=True,
    differentiable=False)


def _sample_poisson(lam, shape=(), dtype="float32", rng=None):
    sh = tuple(lam.shape) + (tuple(shape) if shape else ())
    lam_b = jnp.broadcast_to(
        lam.reshape(lam.shape + (1,) * (len(sh) - lam.ndim)), sh)
    return jax.random.poisson(rng, lam_b).astype(dtype)


_REGISTRY["_sample_poisson"] = Operator(
    "_sample_poisson", _sample_poisson, needs_rng=True,
    differentiable=False)


def _sample_negative_binomial(k, p, shape=(), dtype="float32", rng=None):
    """NB(k, p) == Poisson(Gamma(k, (1-p)/p)) (the reference's
    gamma-poisson mixture, src/operator/random/sampler.h)."""
    sh = tuple(k.shape) + (tuple(shape) if shape else ())
    expand = (1,) * (len(sh) - k.ndim)
    kk = jnp.broadcast_to(k.reshape(k.shape + expand), sh)
    pp = jnp.broadcast_to(p.reshape(p.shape + expand), sh)
    kg, kp = jax.random.split(rng)
    lam = jax.random.gamma(kg, kk) * (1 - pp) / pp
    return jax.random.poisson(kp, lam).astype(dtype)


_REGISTRY["_sample_negative_binomial"] = Operator(
    "_sample_negative_binomial", _sample_negative_binomial, needs_rng=True,
    differentiable=False)


def _sample_gnb(mu, alpha, shape=(), dtype="float32", rng=None):
    """Generalized NB via gamma-poisson with mean mu, dispersion alpha."""
    sh = tuple(mu.shape) + (tuple(shape) if shape else ())
    expand = (1,) * (len(sh) - mu.ndim)
    m = jnp.broadcast_to(mu.reshape(mu.shape + expand), sh)
    a = jnp.broadcast_to(alpha.reshape(alpha.shape + expand), sh)
    kg, kp = jax.random.split(rng)
    r = 1.0 / jnp.maximum(a, 1e-12)
    lam = jax.random.gamma(kg, r) * m / r
    return jax.random.poisson(kp, lam).astype(dtype)


_REGISTRY["_sample_generalized_negative_binomial"] = Operator(
    "_sample_generalized_negative_binomial", _sample_gnb, needs_rng=True,
    differentiable=False)


# ------------------------------------------------- optimizer kernel tail ---

def _clip(g, c):
    return jnp.clip(g, -c, c) if c and c > 0 else g


def _ftml_update(weight, grad, d, v, z, lr=0.01, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                 clip_grad=-1.0):
    """reference: optimizer_op-inl.h FTMLKernel (formula transcribed from
    the paper per the reference's semantics)."""
    g = _clip(rescale_grad * grad, clip_grad) + wd * weight
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * \
        (jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    z_new = beta1 * z + (1 - beta1) * g - (d_t - beta1 * d) * weight
    return -z_new / d_t, d_t, v_new, z_new


_reg("ftml_update", _ftml_update, nout=4, mutates=(0, 2, 3, 4))


def _mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _clip(rescale_grad * grad, clip_gradient).astype(jnp.float32) + \
        wd * weight32
    mom_new = momentum * mom - lr * g
    w32 = weight32 + momentum * mom_new - lr * g
    return w32.astype(weight.dtype), mom_new, w32


_reg("mp_nag_mom_update", _mp_nag_mom_update, nout=3, mutates=(0, 2, 3))


def _adamw_update(weight, grad, mean, var, rescale_grad=1.0, lr=0.01,
                  beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                  clip_gradient=-1.0):
    """reference: src/operator/contrib/adamw.cc (decoupled weight decay)."""
    g = _clip(jnp.asarray(rescale_grad) * grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight)
    return w, m, v


_reg("_adamw_update", _adamw_update, nout=3, mutates=(0, 2, 3))


def _mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad=1.0,
                     lr=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8,
                     wd=0.0, eta=1.0, clip_gradient=-1.0):
    g = _clip(jnp.asarray(rescale_grad) * grad,
              clip_gradient).astype(jnp.float32)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - eta * (lr * m / (jnp.sqrt(v) + epsilon) +
                            wd * weight32)
    return w32.astype(weight.dtype), m, v, w32


_reg("_mp_adamw_update", _mp_adamw_update, nout=4, mutates=(0, 2, 3, 4))


def _mp_lamb_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                    beta2=0.999, epsilon=1e-6, t=1, bias_correction=True,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """reference: optimizer_op.cc mp_lamb_update_phase1."""
    g = _clip(rescale_grad * grad, clip_gradient).astype(jnp.float32)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh = m / (1 - beta1 ** t)
        vh = v / (1 - beta2 ** t)
    else:
        mh, vh = m, v
    return mh / (jnp.sqrt(vh) + epsilon) + wd * weight32, m, v


_reg("mp_lamb_update_phase1", _mp_lamb_phase1, nout=3, mutates=(2, 3))


def _mp_lamb_phase2(weight, g, r1, r2, weight32, lr=0.01,
                    lower_bound=-1.0, upper_bound=-1.0):
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    if lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    w32 = weight32 - lr * ratio * g
    return w32.astype(weight.dtype), w32


_reg("mp_lamb_update_phase2", _mp_lamb_phase2, nout=2, mutates=(0, 4))


def _multi_sgd_like(arrays, n_per, update, num_weights=1, lrs=(),
                    wds=(), **kw):
    outs = []
    for i in range(num_weights):
        group = arrays[i * n_per:(i + 1) * n_per]
        outs.extend(update(group, float(lrs[i]), float(wds[i]), **kw))
    return tuple(outs)


def _multi_sgd_update(arrays, num_weights=1, lrs=(), wds=(),
                      rescale_grad=1.0, clip_gradient=-1.0):
    """reference: optimizer_op.cc multi_sgd_update — functional form:
    returns the updated weights (the reference writes in place)."""
    def upd(group, lr, wd):
        w, g = group
        gg = _clip(rescale_grad * g, clip_gradient)
        return [w - lr * (gg + wd * w)]
    return _multi_sgd_like(arrays, 2, upd, num_weights, lrs, wds)


_reg("multi_sgd_update", _multi_sgd_update, variadic=True, nout=2,
     differentiable=False)


def _multi_sgd_mom_update(arrays, num_weights=1, lrs=(), wds=(),
                          momentum=0.0, rescale_grad=1.0,
                          clip_gradient=-1.0):
    def upd(group, lr, wd):
        w, g, m = group
        gg = _clip(rescale_grad * g, clip_gradient)
        m_new = momentum * m - lr * (gg + wd * w)
        return [w + m_new, m_new]
    return _multi_sgd_like(arrays, 3, upd, num_weights, lrs, wds)


_reg("multi_sgd_mom_update", _multi_sgd_mom_update, variadic=True, nout=2,
     differentiable=False)


def _multi_mp_sgd_update(arrays, num_weights=1, lrs=(), wds=(),
                         rescale_grad=1.0, clip_gradient=-1.0):
    def upd(group, lr, wd):
        w, g, w32 = group
        gg = _clip(rescale_grad * g, clip_gradient).astype(jnp.float32)
        new32 = w32 - lr * (gg + wd * w32)
        return [new32.astype(w.dtype), new32]
    return _multi_sgd_like(arrays, 3, upd, num_weights, lrs, wds)


_reg("multi_mp_sgd_update", _multi_mp_sgd_update, variadic=True, nout=2,
     differentiable=False)


def _multi_mp_sgd_mom_update(arrays, num_weights=1, lrs=(), wds=(),
                             momentum=0.0, rescale_grad=1.0,
                             clip_gradient=-1.0):
    def upd(group, lr, wd):
        w, g, m, w32 = group
        gg = _clip(rescale_grad * g, clip_gradient).astype(jnp.float32)
        m_new = momentum * m - lr * (gg + wd * w32)
        new32 = w32 + m_new
        return [new32.astype(w.dtype), m_new, new32]
    return _multi_sgd_like(arrays, 4, upd, num_weights, lrs, wds)


_reg("multi_mp_sgd_mom_update", _multi_mp_sgd_mom_update, variadic=True,
     nout=2, differentiable=False)


def _multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
                eps=1e-8, rescale_grad=1.0):
    """reference: optimizer_op.cc multi_lars — layerwise LR scaling."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = eta * w_norm / (g_norm + wds * w_norm + eps)
    return jnp.where(jnp.logical_and(w_norm > 0, g_norm > 0),
                     lrs * ratio, lrs)


_reg("multi_lars", _multi_lars, differentiable=False)


# ------------------------------------------------------- small contribs ----

_reg("_contrib_allclose",
     lambda a, b, rtol=1e-5, atol=1e-8, equal_nan=False:
     jnp.allclose(a, b, rtol=rtol, atol=atol,
                  equal_nan=equal_nan)[None].astype(jnp.float32),
     differentiable=False)
def _arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    n = _np.prod(data.shape) if axis is None else data.shape[axis]
    r = int(repeat)
    # reference semantics (np_init_op.cc _npi_arange_like): each value is
    # emitted `repeat` times, so n outputs cover ceil(n/repeat) steps
    vals = jnp.repeat(jnp.arange(-(-n // r), dtype=data.dtype) * step
                      + start, r)[:n]
    return vals.reshape(data.shape if axis is None else (-1,))


_reg("_contrib_arange_like", _arange_like, differentiable=False)
_reg("_contrib_div_sqrt_dim",
     lambda data: data / jnp.sqrt(jnp.asarray(data.shape[-1],
                                              data.dtype)))


def _quadratic(data, a=0.0, b=0.0, c=0.0):
    """reference: src/operator/contrib/quadratic_op.cc (the tutorial op)."""
    return a * jnp.square(data) + b * data + c


_reg("_contrib_quadratic", _quadratic)


@jax.custom_vjp
def _gradmult_core(data, scalar):
    return data


def _gm_fwd(data, scalar):
    return data, scalar


def _gm_bwd(scalar, g):
    return g * scalar, None


_gradmult_core.defvjp(_gm_fwd, _gm_bwd)
_reg("_contrib_gradientmultiplier",
     lambda data, scalar=1.0: _gradmult_core(data, scalar))


def _index_array(data, axes=None):
    """reference: contrib/index_array.cc — per-element N-d indices."""
    shape = data.shape
    idx = jnp.stack(jnp.meshgrid(
        *[jnp.arange(s) for s in shape], indexing="ij"), axis=-1)
    if axes is not None:
        idx = idx[..., list(axes)]
    return idx.astype(jnp.int64)


_reg("_contrib_index_array", _index_array, differentiable=False)


def _index_copy(old, idx, new):
    """reference: contrib/index_copy.cc."""
    return old.at[idx.astype(jnp.int32)].set(new)


_reg("_contrib_index_copy", _index_copy)

_reg("_contrib_edge_id",
     lambda data, u, v: data[u.astype(jnp.int32), v.astype(jnp.int32)],
     differentiable=False)


def _box_encode(samples, matches, anchors, refs, means=None, stds=None):
    """reference: contrib/bounding_box.cc box_encode: encode matched
    (corner) refs against (corner) anchors into normalized offsets."""
    means = jnp.asarray(means if means is not None
                        else (0.0, 0.0, 0.0, 0.0))
    stds = jnp.asarray(stds if stds is not None else (0.1, 0.1, 0.2, 0.2))
    ref = jnp.take_along_axis(
        refs, jnp.maximum(matches, 0)[..., None].astype(jnp.int32),
        axis=-2)
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = (anchors[..., 0] + anchors[..., 2]) / 2
    ay = (anchors[..., 1] + anchors[..., 3]) / 2
    gw = ref[..., 2] - ref[..., 0]
    gh = ref[..., 3] - ref[..., 1]
    gx = (ref[..., 0] + ref[..., 2]) / 2
    gy = (ref[..., 1] + ref[..., 3]) / 2
    t = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                   jnp.log(jnp.maximum(gw, 1e-12) / aw),
                   jnp.log(jnp.maximum(gh, 1e-12) / ah)], axis=-1)
    t = (t - means) / stds
    valid = (samples > 0.5)[..., None]
    return jnp.where(valid, t, 0.0), jnp.broadcast_to(
        valid, t.shape).astype(t.dtype)


_reg("_contrib_box_encode", _box_encode, nout=2, differentiable=False)


def _box_decode(data, anchors, std0=1.0, std1=1.0, std2=1.0, std3=1.0,
                clip=-1.0, format="corner"):
    """reference: contrib/bounding_box.cc box_decode."""
    if format == "corner":
        aw = anchors[..., 2] - anchors[..., 0]
        ah = anchors[..., 3] - anchors[..., 1]
        ax = (anchors[..., 0] + anchors[..., 2]) / 2
        ay = (anchors[..., 1] + anchors[..., 3]) / 2
    else:
        ax, ay, aw, ah = (anchors[..., 0], anchors[..., 1],
                          anchors[..., 2], anchors[..., 3])
    ox = data[..., 0] * std0 * aw + ax
    oy = data[..., 1] * std1 * ah + ay
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    ow = jnp.exp(dw) * aw / 2
    oh = jnp.exp(dh) * ah / 2
    return jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)


_reg("_contrib_box_decode", _box_decode)

_reg("_contrib_fft",
     lambda data, compute_size=128: jnp.concatenate(
         [jnp.real(jnp.fft.fft(data))[..., None],
          jnp.imag(jnp.fft.fft(data))[..., None]],
         axis=-1).reshape(data.shape[:-1] + (2 * data.shape[-1],)))


def _contrib_ifft(data, compute_size=128):
    comp = data.reshape(data.shape[:-1] + (data.shape[-1] // 2, 2))
    return jnp.real(jnp.fft.ifft(comp[..., 0] + 1j * comp[..., 1])) * \
        comp.shape[-2]


_reg("_contrib_ifft", _contrib_ifft)


@jax.custom_vjp
def _round_ste_core(x):
    return jnp.round(x)


_round_ste_core.defvjp(lambda x: (jnp.round(x), None),
                       lambda _, g: (g,))
_reg("_contrib_round_ste", lambda data: _round_ste_core(data))


@jax.custom_vjp
def _sign_ste_core(x):
    return jnp.sign(x)


_sign_ste_core.defvjp(lambda x: (jnp.sign(x), None),
                      lambda _, g: (g,))
_reg("_contrib_sign_ste", lambda data: _sign_ste_core(data))


# ===================================================================
# round-3 tail: transformer interleaved matmuls, image frontend ops,
# npx/npi internals, packed-triangular linalg, scatter family, sync BN,
# correlation, count-sketch, bipartite matching.
# ===================================================================

# ---------------------------------------------------- transformer ----
# reference: src/operator/contrib/transformer.cc:650-780. Layouts:
# qkv (T, B, 3*H*D) interleaved; attention maps (B*H, Tq, Tk).

def _selfatt_split(qkv, heads, idx):
    t, b, _ = qkv.shape
    tmp = qkv.reshape(t, b, heads, 3, -1)
    proj = jnp.transpose(tmp[:, :, :, idx, :], (1, 2, 0, 3))
    return proj.reshape(b * heads, t, -1)


def _interleaved_matmul_selfatt_qk(qkv, heads=1):
    q = _selfatt_split(qkv, heads, 0)
    k = _selfatt_split(qkv, heads, 1)
    q = q / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    return jnp.einsum("bqd,bkd->bqk", q, k)


_reg("_contrib_interleaved_matmul_selfatt_qk",
     _interleaved_matmul_selfatt_qk)


def _interleaved_matmul_selfatt_valatt(qkv, att, heads=1):
    t, b, _ = qkv.shape
    v = _selfatt_split(qkv, heads, 2)           # (B*H, T, D)
    out = jnp.einsum("bqk,bkd->bqd", att, v)
    out = out.reshape(b, heads, t, -1)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(t, b, -1)


_reg("_contrib_interleaved_matmul_selfatt_valatt",
     _interleaved_matmul_selfatt_valatt)


def _encdec_split(kv, heads, idx):
    t, b, _ = kv.shape
    tmp = kv.reshape(t, b, heads, 2, -1)
    proj = jnp.transpose(tmp[:, :, :, idx, :], (1, 2, 0, 3))
    return proj.reshape(b * heads, t, -1)


def _interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    tq, b, _ = queries.shape
    q = jnp.transpose(queries.reshape(tq, b, heads, -1), (1, 2, 0, 3))
    q = q.reshape(b * heads, tq, -1)
    q = q / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    k = _encdec_split(keys_values, heads, 0)
    return jnp.einsum("bqd,bkd->bqk", q, k)


_reg("_contrib_interleaved_matmul_encdec_qk",
     _interleaved_matmul_encdec_qk)


def _interleaved_matmul_encdec_valatt(keys_values, att, heads=1):
    tk, b, _ = keys_values.shape
    v = _encdec_split(keys_values, heads, 1)
    out = jnp.einsum("bqk,bkd->bqd", att, v)
    tq = out.shape[1]
    out = out.reshape(b, heads, tq, -1)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(tq, b, -1)


_reg("_contrib_interleaved_matmul_encdec_valatt",
     _interleaved_matmul_encdec_valatt)


# -------------------------------------------------- image frontend ----
# reference: src/operator/image/ (crop.cc, resize.cc, image_random.cc).
# HWC (or NHWC) uint8/float images, matching mx.image semantics.

def _image_crop(data, x=0, y=0, width=1, height=1):
    if data.ndim == 3:
        return lax.dynamic_slice(
            data, (y, x, 0), (height, width, data.shape[2]))
    return lax.dynamic_slice(
        data, (0, y, x, 0),
        (data.shape[0], height, width, data.shape[3]))


_reg("_image_crop", _image_crop)


def _image_resize(data, size=None, keep_ratio=False, interp=1):
    import jax.image as jimage
    if isinstance(size, int):
        size = (size, size)
    h, w = int(size[1]), int(size[0])     # reference size is (w, h)
    method = "nearest" if interp == 0 else "linear"
    if data.ndim == 3:
        out = jimage.resize(data.astype(jnp.float32),
                            (h, w, data.shape[2]), method=method)
    else:
        out = jimage.resize(data.astype(jnp.float32),
                            (data.shape[0], h, w, data.shape[3]),
                            method=method)
    return out.astype(data.dtype) if jnp.issubdtype(
        data.dtype, jnp.integer) else out


_reg("_image_resize", _image_resize)


def _image_to_tensor(data):
    x = data.astype(jnp.float32) / 255.0
    if data.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


_reg("_image_to_tensor", _image_to_tensor)


def _image_normalize(data, mean=0.0, std=1.0):
    # CHW (or NCHW) float input, per-channel mean/std
    mean = jnp.asarray(mean, data.dtype)
    std = jnp.asarray(std, data.dtype)
    shape = ((-1, 1, 1) if data.ndim == 3 else (1, -1, 1, 1))
    if mean.ndim:
        mean = mean.reshape(shape)
    if std.ndim:
        std = std.reshape(shape)
    return (data - mean) / std


_reg("_image_normalize", _image_normalize)


# ------------------------------------------------------ npx tail ----
# reference: src/operator/numpy/npx_*.cc internals backing mx.npx.

_reg("_npx_relu", lambda data: jnp.maximum(data, 0))
_reg("_npx_sigmoid", lambda data: jax.nn.sigmoid(data))


def _npx_reshape(data, newshape=None, reverse=False, order="C"):
    """npx.reshape special codes (reference: np_matrix_op.cc): -1 infer,
    -2 copy all remaining dims, 0 copy this dim."""
    shape = list(newshape)
    if reverse:
        shape = shape[::-1]
        src = list(data.shape)[::-1]
    else:
        src = list(data.shape)
    out = []
    si = 0
    for s in shape:
        if s == 0:
            out.append(src[si])
            si += 1
        elif s == -2:
            out.extend(src[si:])
            si = len(src)
        else:
            out.append(s)
            if s != -1:
                si += 1
    if reverse:
        out = out[::-1]
    return data.reshape(tuple(out))


_reg("_npx_reshape", _npx_reshape)


def _npx_nonzero(data):
    # dynamic output shape: eager/host only (reference marks it
    # dynamic-shape too)
    idx = _np.nonzero(_np.asarray(data))
    return jnp.asarray(_np.stack(idx, axis=-1), jnp.int64)


_reg("_npx_nonzero", _npx_nonzero, host_op=True, differentiable=False)


def _npx_constraint_check(data, msg="constraint violated"):
    ok = jnp.all(data)
    if not isinstance(ok, jax.core.Tracer) and not bool(ok):
        raise ValueError(str(msg))
    return ok


_reg("_npx_constraint_check", _npx_constraint_check,
     differentiable=False)


# ------------------------------------------------------ npi tail ----

_reg("_npi_where_lscalar",
     lambda cond, x, scalar=0.0: jnp.where(cond, x, scalar))
_reg("_npi_where_rscalar",
     lambda cond, y, scalar=0.0: jnp.where(cond, scalar, y))
_reg("_npi_where_scalar2",
     lambda cond, x=0.0, y=0.0: jnp.where(
         cond, jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32)))
_reg("_npi_powerd", lambda a, exp=1.0: jnp.power(a, exp))
# numpy-semantics matmul (reference: _npi_matmul, src/operator/numpy/
# np_matmul_op.cc) — broadcasting batch matmul, the ONNX MatMul contract
_reg("_npi_matmul", lambda a, b: jnp.matmul(a, b))
_reg("_npi_tensordot_int_axes",
     lambda a, b, axes=2: jnp.tensordot(a, b, axes=int(axes)))
_reg("_npi_matrix_rank_none_tol",
     lambda M, hermitian=False: jnp.linalg.matrix_rank(M),
     differentiable=False)
_reg("_npi_pinv_scalar_rcond",
     lambda a, rcond=1e-15: jnp.linalg.pinv(a, rcond=float(rcond)))


def _npi_boolean_mask_assign_scalar(data, mask, value=0.0):
    return jnp.where(mask.astype(bool), jnp.asarray(value, data.dtype),
                     data)


_reg("_npi_boolean_mask_assign_scalar", _npi_boolean_mask_assign_scalar)


def _npi_boolean_mask_assign_tensor(data, mask, value):
    m = mask.astype(bool)
    # value holds one entry per True position (numpy fancy-assign
    # semantics): scatter them in mask order — host path for the
    # dynamic count, mirroring the reference's dynamic-shape op
    mnp = _np.asarray(m)
    out = _np.asarray(data).copy()
    out[mnp] = _np.asarray(value).reshape(-1)[:int(mnp.sum())] \
        if _np.asarray(value).size != out[mnp].size else \
        _np.asarray(value).reshape(out[mnp].shape)
    return jnp.asarray(out)


_reg("_npi_boolean_mask_assign_tensor", _npi_boolean_mask_assign_tensor,
     host_op=True, differentiable=False)


def _npi_insert_slice(data, obj=0, values=0.0, axis=None, **kw):
    return jnp.asarray(_np.insert(_np.asarray(data), int(obj),
                                  _np.asarray(values), axis=axis))


_reg("_npi_insert_slice", _npi_insert_slice, host_op=True,
     differentiable=False)


def _npi_insert_tensor(data, obj, values=0.0, axis=None, **kw):
    return jnp.asarray(_np.insert(_np.asarray(data),
                                  _np.asarray(obj).astype(_np.int64),
                                  _np.asarray(values), axis=axis))


_reg("_npi_insert_tensor", _npi_insert_tensor, host_op=True,
     differentiable=False)


def _npi_share_memory(a, b):
    try:
        same = a.unsafe_buffer_pointer() == b.unsafe_buffer_pointer()
    except Exception:
        same = a is b
    return jnp.asarray(same)


_reg("_npi_share_memory", _npi_share_memory, host_op=True,
     differentiable=False)


def _npi_uniform_n(low=0.0, high=1.0, rng=None, size=None,
                   dtype="float32"):
    from ..base import dtype_np
    shape = tuple(size) if size is not None else ()
    return jax.random.uniform(rng, shape, dtype_np(dtype),
                              minval=low, maxval=high)


_REGISTRY["_npi_uniform_n"] = Operator(
    "_npi_uniform_n", _npi_uniform_n, needs_rng=True,
    differentiable=False)


def _npi_normal_n(loc=0.0, scale=1.0, rng=None, size=None,
                  dtype="float32"):
    from ..base import dtype_np
    shape = tuple(size) if size is not None else ()
    return loc + scale * jax.random.normal(rng, shape, dtype_np(dtype))


_REGISTRY["_npi_normal_n"] = Operator(
    "_npi_normal_n", _npi_normal_n, needs_rng=True, differentiable=False)


# ------------------------------------------- packed triangular linalg --
# reference: src/operator/linalg/ extracttrian/maketrian (packed storage
# of triangular matrices).

def _tri_indices(n, offset, lower):
    if lower:
        return _np.tril_indices(n, k=offset)
    return _np.triu_indices(n, k=offset)


def _linalg_extracttrian(A, offset=0, lower=True):
    n = A.shape[-1]
    rows, cols = _tri_indices(n, offset if not lower else offset, lower)
    return A[..., rows, cols]


_reg("_linalg_extracttrian", _linalg_extracttrian)


def _linalg_maketrian(a, offset=0, lower=True):
    # invert extracttrian: packed vector of length n*(n+1)/2-ish -> matrix
    m = a.shape[-1]
    # solve n from m given the diagonal offset
    k = abs(offset)
    n = int(round((_np.sqrt(8 * m + (2 * k - 1) ** 2) - 1) / 2)) + \
        (k if offset else 0)
    # find n by search (robust for any offset)
    for cand in range(1, m + k + 2):
        if len(_tri_indices(cand, offset, lower)[0]) == m:
            n = cand
            break
    rows, cols = _tri_indices(n, offset, lower)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., rows, cols].set(a)


_reg("_linalg_maketrian", _linalg_maketrian)


# ------------------------------------------------- scatter family ----
# reference: src/operator/tensor/indexing_op.cc _scatter_set_nd,
# elemwise_binary_op_basic.cc _scatter_elemwise_div: the "apply only on
# stored (nonzero) positions" kernels backing sparse arithmetic.

def _scatter_set_nd(lhs, rhs, indices, shape=None):
    idx = tuple(indices[i].astype(jnp.int32)
                for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


_reg("_scatter_set_nd", _scatter_set_nd)

_reg("_scatter_elemwise_div",
     lambda lhs, rhs: jnp.where(lhs != 0, lhs / rhs,
                                jnp.zeros((), lhs.dtype)))
_reg("_scatter_minus_scalar",
     lambda data, scalar=0.0: jnp.where(
         data != 0, data - jnp.asarray(scalar, data.dtype),
         jnp.zeros((), data.dtype)))
_reg("_scatter_plus_scalar",
     lambda data, scalar=0.0: jnp.where(
         data != 0, data + jnp.asarray(scalar, data.dtype),
         jnp.zeros((), data.dtype)))


# ------------------------------------------------------- misc tail ----

_reg("_zeros_without_dtype",
     lambda shape=(), ctx=None, dtype=None: jnp.zeros(
         tuple(shape), jnp.float32),
     differentiable=False)


def _rnn_param_concat(arrays, dim=0):
    return jnp.concatenate(arrays, axis=int(dim))


_REGISTRY["_rnn_param_concat"] = Operator(
    "_rnn_param_concat", _rnn_param_concat, variadic=True)


def _contrib_boolean_mask(data, index, axis=0, size=None):
    """Dynamic output shape. Eager: exact (host compress, like the
    reference's runtime shape re-inference). Under an
    ``npx.dynamic_shape_bound`` (or explicit ``size=``): fixed-size
    output padded with zero rows — jit-compatible."""
    if size is None:
        from ..numpy_extension.dynamic import current_shape_bound
        size = current_shape_bound()
    if size is not None:
        sel = jnp.asarray(index).astype(bool)
        (idx,) = jnp.where(sel, size=int(size), fill_value=-1)
        taken = jnp.take(data, jnp.maximum(idx, 0), axis=axis)
        shape = [1] * taken.ndim
        shape[axis] = int(size)
        # select (not multiply): 0*inf/0*nan would leak NaN into the
        # zero-padded rows
        return jnp.where((idx >= 0).reshape(shape), taken,
                         jnp.zeros((), taken.dtype))
    sel = _np.asarray(index).astype(bool)
    return jnp.asarray(_np.compress(sel, _np.asarray(data), axis=axis))


_reg("_contrib_boolean_mask", _contrib_boolean_mask, host_op=True,
     differentiable=False)


def _contrib_getnnz(data, axis=None):
    return jnp.count_nonzero(data, axis=axis).astype(jnp.int64)


_reg("_contrib_getnnz", _contrib_getnnz, differentiable=False)


def _identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                   penalty=0.001, momentum=0.9):
    """Forward identity (reference: src/operator/regression_output...
    identity_attach_KL_sparse_reg.cc). The KL sparsity penalty is a
    training-loss addend in the reference; in this framework add the
    penalty to the loss explicitly — the op passes data through so
    reference model definitions load."""
    return data


_reg("IdentityAttachKLSparseReg", _identity_attach_kl_sparse_reg)


def _contrib_count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection (reference:
    src/operator/contrib/count_sketch.cc): out[:, h[j]] += s[j]*data[:, j].
    """
    h = h.reshape(-1).astype(jnp.int32)
    s = s.reshape(-1).astype(data.dtype)
    contrib = data * s[None, :]
    out = jnp.zeros((data.shape[0], int(out_dim)), data.dtype)
    return out.at[:, h].add(contrib)


_reg("_contrib_count_sketch", _contrib_count_sketch)


def _contrib_bipartite_matching(data, threshold=1e-12, is_ascend=False,
                                topk=-1):
    """Greedy bipartite matching (reference:
    src/operator/contrib/bipartite_matching.cc): returns (row->col
    matches, col->row matches), -1 for unmatched. Host op (inherently
    sequential argmax-and-mask loop)."""
    scores = _np.asarray(data)
    squeeze = scores.ndim == 2
    if squeeze:
        scores = scores[None]
    b, n, m = scores.shape
    row_match = _np.full((b, n), -1, _np.float32)
    col_match = _np.full((b, m), -1, _np.float32)
    for i in range(b):
        sc = scores[i].copy()
        order = _np.argsort(sc.ravel())
        if not is_ascend:
            order = order[::-1]
        k = 0
        limit = topk if topk > 0 else min(n, m)
        for flat in order:
            r, c = divmod(int(flat), m)
            val = sc[r, c]
            if (not is_ascend and val < threshold) or \
                    (is_ascend and val > threshold):
                break
            if row_match[i, r] >= 0 or col_match[i, c] >= 0:
                continue
            row_match[i, r] = c
            col_match[i, c] = r
            k += 1
            if k >= limit:
                break
    if squeeze:
        row_match, col_match = row_match[0], col_match[0]
    return jnp.asarray(row_match), jnp.asarray(col_match)


_reg("_contrib_bipartite_matching", _contrib_bipartite_matching, nout=2,
     host_op=True, differentiable=False)


# ------------------------------------- preloaded / multi optimizer tail --
# reference: optimizer_op.cc preloaded_multi_sgd_* (lrs/wds arrive as
# tensors, the last two inputs), contrib/adamw.cc _multi_adamw_update,
# contrib/optimizer_op.cc _multi_lamb_update, group_adagrad,
# optimizer_op.cc _sparse_adagrad_update.

def _preloaded_like(arrays, n_per, upd):
    lrs, wds = arrays[-2], arrays[-1]
    body = arrays[:-2]
    num = len(body) // n_per
    outs = []
    for i in range(num):
        group = body[i * n_per:(i + 1) * n_per]
        outs.extend(upd(group, lrs[i], wds[i]))
    return tuple(outs)


def _preloaded_multi_sgd_update(arrays, rescale_grad=1.0,
                                clip_gradient=-1.0, **kw):
    def upd(group, lr, wd):
        w, g = group
        gg = _clip(rescale_grad * g, clip_gradient)
        return [w - lr * (gg + wd * w)]
    return _preloaded_like(arrays, 2, upd)


_reg("preloaded_multi_sgd_update", _preloaded_multi_sgd_update,
     variadic=True, nout=2, differentiable=False)


def _preloaded_multi_sgd_mom_update(arrays, momentum=0.0,
                                    rescale_grad=1.0, clip_gradient=-1.0,
                                    **kw):
    def upd(group, lr, wd):
        w, g, m = group
        gg = _clip(rescale_grad * g, clip_gradient)
        m_new = momentum * m - lr * (gg + wd * w)
        return [w + m_new, m_new]
    return _preloaded_like(arrays, 3, upd)


_reg("preloaded_multi_sgd_mom_update", _preloaded_multi_sgd_mom_update,
     variadic=True, nout=2, differentiable=False)


def _preloaded_multi_mp_sgd_update(arrays, rescale_grad=1.0,
                                   clip_gradient=-1.0, **kw):
    def upd(group, lr, wd):
        w, g, w32 = group
        gg = _clip(rescale_grad * g, clip_gradient).astype(jnp.float32)
        new32 = w32 - lr * (gg + wd * w32)
        return [new32.astype(w.dtype), new32]
    return _preloaded_like(arrays, 3, upd)


_reg("preloaded_multi_mp_sgd_update", _preloaded_multi_mp_sgd_update,
     variadic=True, nout=2, differentiable=False)


def _preloaded_multi_mp_sgd_mom_update(arrays, momentum=0.0,
                                       rescale_grad=1.0,
                                       clip_gradient=-1.0, **kw):
    def upd(group, lr, wd):
        w, g, m, w32 = group
        gg = _clip(rescale_grad * g, clip_gradient).astype(jnp.float32)
        m_new = momentum * m - lr * (gg + wd * w32)
        new32 = w32 + m_new
        return [new32.astype(w.dtype), m_new, new32]
    return _preloaded_like(arrays, 4, upd)


_reg("preloaded_multi_mp_sgd_mom_update",
     _preloaded_multi_mp_sgd_mom_update, variadic=True, nout=2,
     differentiable=False)


def _adamw_step(w32, g, m, v, lr, eta, wd, beta1, beta2, epsilon,
                rescale, clip_gradient):
    gg = _clip(g.astype(jnp.float32) * rescale, clip_gradient)
    m_new = beta1 * m + (1 - beta1) * gg
    v_new = beta2 * v + (1 - beta2) * gg * gg
    new32 = w32 - eta * (lr * m_new / (jnp.sqrt(v_new) + epsilon)
                         + wd * w32)
    return new32, m_new, v_new


def _multi_adamw_update(arrays, lrs=(), wds=(), etas=(), beta1=0.9,
                        beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                        **kw):
    """reference: contrib/adamw.cc _multi_adamw_update — last input is
    the dynamic rescale_grad scalar tensor."""
    rescale = arrays[-1].reshape(())
    body = arrays[:-1]
    outs = []
    for i in range(len(body) // 4):
        w, g, m, v = body[i * 4:(i + 1) * 4]
        new32, m_new, v_new = _adamw_step(
            w.astype(jnp.float32), g, m, v, float(lrs[i]),
            float(etas[i]), float(wds[i]), beta1, beta2, epsilon,
            rescale, clip_gradient)
        outs.extend([new32.astype(w.dtype), m_new, v_new])
    return tuple(outs)


_reg("_multi_adamw_update", _multi_adamw_update, variadic=True, nout=2,
     differentiable=False)


def _multi_mp_adamw_update(arrays, lrs=(), wds=(), etas=(), beta1=0.9,
                           beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                           **kw):
    rescale = arrays[-1].reshape(())
    body = arrays[:-1]
    outs = []
    for i in range(len(body) // 5):
        w, g, m, v, w32 = body[i * 5:(i + 1) * 5]
        new32, m_new, v_new = _adamw_step(
            w32, g, m, v, float(lrs[i]), float(etas[i]), float(wds[i]),
            beta1, beta2, epsilon, rescale, clip_gradient)
        outs.extend([new32.astype(w.dtype), m_new, v_new, new32])
    return tuple(outs)


_reg("_multi_mp_adamw_update", _multi_mp_adamw_update, variadic=True,
     nout=2, differentiable=False)


def _lamb_step(w32, g, m, v, lr, wd, beta1, beta2, epsilon, t,
               rescale_grad, clip_gradient, lower_bound, upper_bound):
    gg = _clip(g.astype(jnp.float32) * rescale_grad, clip_gradient)
    m_new = beta1 * m + (1 - beta1) * gg
    v_new = beta2 * v + (1 - beta2) * gg * gg
    mhat = m_new / (1 - beta1 ** t)
    vhat = v_new / (1 - beta2 ** t)
    gdash = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w32
    wnorm = jnp.linalg.norm(w32)
    gnorm = jnp.linalg.norm(gdash)
    ratio = jnp.where(gnorm > 0, wnorm / gnorm, 1.0)
    if lower_bound is not None and lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    ratio = jnp.where(wnorm > 0, ratio, 1.0)
    return w32 - lr * ratio * gdash, m_new, v_new


def _multi_lamb_update(arrays, learning_rates=(), wds=(), beta1=0.9,
                       beta2=0.999, epsilon=1e-6, step_count=(),
                       rescale_grad=1.0, clip_gradient=-1.0,
                       lower_bound=-1.0, upper_bound=-1.0, **kw):
    """reference: contrib/optimizer_op.cc multi_lamb_update."""
    outs = []
    for i in range(len(arrays) // 4):
        w, g, m, v = arrays[i * 4:(i + 1) * 4]
        new32, m_new, v_new = _lamb_step(
            w.astype(jnp.float32), g, m, v, float(learning_rates[i]),
            float(wds[i]), beta1, beta2, epsilon, int(step_count[i]),
            rescale_grad, clip_gradient,
            lower_bound if lower_bound > 0 else None,
            upper_bound if upper_bound > 0 else None)
        outs.extend([new32.astype(w.dtype), m_new, v_new])
    return tuple(outs)


_reg("_multi_lamb_update", _multi_lamb_update, variadic=True, nout=2,
     differentiable=False)


def _multi_mp_lamb_update(arrays, learning_rates=(), wds=(), beta1=0.9,
                          beta2=0.999, epsilon=1e-6, step_count=(),
                          rescale_grad=1.0, clip_gradient=-1.0,
                          lower_bound=-1.0, upper_bound=-1.0, **kw):
    outs = []
    for i in range(len(arrays) // 5):
        w, g, m, v, w32 = arrays[i * 5:(i + 1) * 5]
        new32, m_new, v_new = _lamb_step(
            w32, g, m, v, float(learning_rates[i]), float(wds[i]),
            beta1, beta2, epsilon, int(step_count[i]), rescale_grad,
            clip_gradient, lower_bound if lower_bound > 0 else None,
            upper_bound if upper_bound > 0 else None)
        outs.extend([new32.astype(w.dtype), m_new, v_new, new32])
    return tuple(outs)


_reg("_multi_mp_lamb_update", _multi_mp_lamb_update, variadic=True,
     nout=2, differentiable=False)


def _sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7,
                           wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """reference: optimizer_op.cc _sparse_adagrad_update (lazy adagrad
    for row-sparse grads). At this dense registry boundary the lazy
    semantics hold structurally: rows with all-zero gradient are
    untouched (their history addend is 0 and the masked update is 0);
    RowSparseNDArray grads take the optimizer-level fast path
    (optimizer.py _update_rsp) before reaching here."""
    g = _clip(rescale_grad * grad, clip_gradient)
    row_nonzero = jnp.any(g != 0, axis=tuple(range(1, g.ndim)),
                          keepdims=True)
    h_new = history + g * g
    upd = lr * g / (jnp.sqrt(h_new) + epsilon) + lr * wd * weight
    return jnp.where(row_nonzero, weight - upd, weight), h_new


_reg("_sparse_adagrad_update", _sparse_adagrad_update, nout=2,
     mutates=(0, 2), differentiable=False)


def _group_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-5,
                          rescale_grad=1.0, clip_gradient=-1.0):
    """reference: contrib/optimizer_op.cc group_adagrad_update — one
    accumulator per row (group), mean of squared grads."""
    g = _clip(rescale_grad * grad, clip_gradient)
    red = tuple(range(1, g.ndim))
    h_new = history + jnp.mean(g * g, axis=red).reshape(history.shape)
    scale = (jnp.sqrt(h_new) + epsilon).reshape(
        (-1,) + (1,) * (g.ndim - 1))
    return weight - lr * g / scale, h_new


_reg("_contrib_group_adagrad_update", _group_adagrad_update, nout=2,
     mutates=(0, 2), differentiable=False)
