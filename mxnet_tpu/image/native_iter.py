"""ImageRecordIterNative: the C++ decode/augment pipeline as a DataIter.

The native analogue of the reference's ImageRecordIter (reference:
src/io/iter_image_recordio_2.cc:887 — worker threads decode JPEG and
augment into pre-staged batch buffers; Python only sees full batches).
Policy (shuffle order, sharding, padding) lives here; the C++ side
(native/src/imagepipe_native.cpp) does the bandwidth-heavy work.

Unlike the reference, batches are bit-deterministic for a fixed seed
regardless of preprocess_threads, because per-sample RNG is keyed on
(epoch_seed, sample_index) rather than on worker-thread state.
"""
from __future__ import annotations

import ctypes
import os

import numpy as _np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import array as nd_array

__all__ = ["ImageRecordIterNative", "native_pipeline_available"]


def _load_idx(path_imgidx):
    offsets = []
    with open(path_imgidx) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) >= 2:
                offsets.append(int(parts[1]))
    return _np.asarray(offsets, dtype=_np.int64)


def native_pipeline_available():
    from ..native import imagepipe_lib
    return imagepipe_lib() is not None


class ImageRecordIterNative(DataIter):
    """Threaded C++ JPEG decode + augment over a .rec/.idx pair."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, rand_crop=False,
                 rand_mirror=False, resize=0, mean=None, std=None,
                 num_parts=1, part_index=0, preprocess_threads=0,
                 label_width=1, seed=0, layout="NCHW",
                 data_name="data", label_name="softmax_label",
                 last_batch_handle="pad", strict=None):
        super().__init__(batch_size)
        # strict=True: a record that fails to decode (or has the wrong
        # label_width) raises, matching the reference's CHECK semantics
        # (src/io/iter_image_recordio_2.cc label-width CHECK / decode
        # crash). Default (strict=False) warns loudly instead of the old
        # silent zero-fill. Env override: MXNET_TPU_IMAGEPIPE_STRICT=1.
        if strict is None:
            strict = os.environ.get("MXNET_TPU_IMAGEPIPE_STRICT") == "1"
        self._strict = bool(strict)
        self._warned_errors = 0
        from ..native import imagepipe_lib
        lib = imagepipe_lib()
        if lib is None:
            raise MXNetError(
                "native image pipeline unavailable (toolchain or OpenCV "
                "missing, or MXNET_TPU_NATIVE=0); use image.ImageIter")
        self._lib = lib
        data_shape = tuple(int(x) for x in data_shape)
        if layout == "NCHW":
            c, h, w = data_shape
        else:
            h, w, c = data_shape
        self._hwcn = (h, w, c)
        self._nhwc = layout == "NHWC"
        self.data_shape = data_shape
        self.label_width = int(label_width)
        self._seed = int(seed)
        self._epoch = -1
        self._shuffle = shuffle
        self._pad = 0
        self._exhausted = False
        if last_batch_handle not in ("pad", "discard"):
            raise MXNetError(
                f"last_batch_handle={last_batch_handle!r} unsupported "
                "here (pad/discard); use image.ImageIter for roll_over")
        self._discard_last = last_batch_handle == "discard"

        if path_imgidx is None:
            path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
        offsets = _load_idx(path_imgidx)
        if num_parts > 1:
            offsets = offsets[part_index::num_parts]
        if offsets.size == 0:
            raise MXNetError(f"no records indexed by {path_imgidx!r}")
        self._offsets = offsets

        mean_a = std_a = None
        if mean is not None or std is not None:
            mean_a = _np.zeros(c, _np.float32) if mean is None else \
                _np.asarray(mean, _np.float32).reshape(c)
            std_a = _np.ones(c, _np.float32) if std is None else \
                _np.asarray(std, _np.float32).reshape(c)
        self._mean_keepalive = (mean_a, std_a)

        nthreads = preprocess_threads or min(os.cpu_count() or 4, 16)
        f32p = ctypes.POINTER(ctypes.c_float)
        self._h = lib.ip_create(
            path_imgrec.encode(), batch_size, h, w, c, nthreads,
            1 if self._nhwc else 0, int(resize),
            1 if rand_crop else 0, 1 if rand_mirror else 0,
            mean_a.ctypes.data_as(f32p) if mean_a is not None else None,
            std_a.ctypes.data_as(f32p) if std_a is not None else None,
            self.label_width)
        if not self._h:
            raise MXNetError(f"cannot open {path_imgrec!r}")

        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + data_shape,
                                      layout=layout)]
        lshape = (batch_size, self.label_width) if self.label_width > 1 \
            else (batch_size,)
        self.provide_label = [DataDesc(label_name, lshape)]
        self.reset()

    def reset(self):
        self._epoch += 1
        order = self._offsets
        if self._shuffle:
            rng = _np.random.RandomState(self._seed + self._epoch)
            order = order.copy()
            rng.shuffle(order)
        n = order.size
        if self._discard_last:
            self._pad = 0
            order = order[:n - n % self.batch_size]
        else:
            self._pad = (-n) % self.batch_size
            if self._pad:
                order = _np.concatenate([order, order[:self._pad]])
        order = _np.ascontiguousarray(order, _np.int64)
        self._nbatches = order.size // self.batch_size
        self._cursor = 0
        self._exhausted = False
        self._lib.ip_start_epoch(
            self._h, order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            order.size, ctypes.c_uint32((self._seed + self._epoch)
                                        & 0xFFFFFFFF))

    def iter_next(self):
        return self._cursor < self._nbatches

    def next(self):
        if self._exhausted or not self.iter_next():
            self._exhausted = True
            raise StopIteration
        shape = (self.batch_size,) + tuple(self.data_shape)
        data = _np.empty(shape, _np.float32)
        label = _np.empty((self.batch_size, self.label_width), _np.float32)
        f32p = ctypes.POINTER(ctypes.c_float)
        count = self._lib.ip_next_batch(
            self._h, data.ctypes.data_as(f32p),
            label.ctypes.data_as(f32p))
        if count <= 0:
            self._exhausted = True
            raise StopIteration
        self._check_errors()
        self._cursor += 1
        pad = self._pad if self._cursor == self._nbatches else 0
        if self.label_width == 1:
            label = label[:, 0]
        return DataBatch(data=[nd_array(data)],
                         label=[nd_array(label)],
                         pad=pad, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def getpad(self):
        return self._pad if self._cursor == self._nbatches else 0

    @property
    def error_count(self):
        """Records that failed to decode (zero-filled), cumulative."""
        return int(self._lib.ip_error_count(self._h))

    @property
    def last_error(self):
        """Message from the most recent native decode/parse failure."""
        msg = self._lib.ip_last_error(self._h)
        return msg.decode(errors="replace") if msg else ""

    def _check_errors(self):
        """Surface native decode/parse failures instead of training on
        zero-filled images (reference hard-fails here; advisor r4)."""
        n = self.error_count
        if n <= self._warned_errors:
            return
        detail = (f"{n} record(s) failed to decode/parse and were "
                  f"zero-filled; last error: {self.last_error!r}")
        if self._strict:
            raise MXNetError(
                detail + " (strict mode; pass strict=False or unset "
                "MXNET_TPU_IMAGEPIPE_STRICT to tolerate)")
        import logging
        logging.getLogger("mxnet_tpu").warning(
            "ImageRecordIterNative: %s — training data is corrupt or "
            "label_width mismatches; set strict=True to raise", detail)
        self._warned_errors = n

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ip_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
