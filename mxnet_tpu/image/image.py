"""Image loading + pure-Python augmenters + ImageIter.

Reference: python/mxnet/image/image.py (imread/imdecode/imresize,
Augmenter zoo, ImageIter over .rec or .lst). Decode/augment run on host
via cv2 exactly like the reference's CPU path (src/io/image_aug_default.cc
used OpenCV too); batches land on device once per batch.
"""
from __future__ import annotations

import os
import random as pyrandom

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, array as nd_array

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize",
           "random_size_crop", "Augmenter", "SequentialAug", "RandomOrderAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "RandomGrayAug",
           "CreateAugmenter", "ImageIter"]


def _cv2():
    import cv2
    return cv2


def imread(filename, flag=1, to_rgb=True):
    """Read image file → HWC NDArray (reference: image.py imread)."""
    cv2 = _cv2()
    img = cv2.imread(filename, flag)
    if img is None:
        raise MXNetError(f"cannot read image {filename}")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd_array(img)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode encoded bytes → HWC NDArray (reference: image.py imdecode —
    the C++ path was src/io/image_io.cc Imdecode)."""
    cv2 = _cv2()
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8), flag)
    if img is None:
        raise MXNetError("cannot decode image")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd_array(img)


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    a = src.asnumpy() if isinstance(src, NDArray) else src
    out = cv2.resize(a, (w, h), interpolation=_cv_interp(interp))
    return nd_array(out)


def _cv_interp(interp):
    import cv2
    return {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR, 2: cv2.INTER_CUBIC,
            3: cv2.INTER_AREA, 4: cv2.INTER_LANCZOS4}.get(interp,
                                                          cv2.INTER_LINEAR)


def resize_short(src, size, interp=2):
    """Resize shorter edge to size (reference: image.py resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    a = src.asnumpy() if isinstance(src, NDArray) else src
    out = a[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp)
    return nd_array(out)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = pyrandom.randint(0, max(0, w - new_w))
    y0 = pyrandom.randint(0, max(0, h - new_h))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size,
                     interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size,
                     interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * new_ratio)))
        new_h = int(round(_np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    a = src.asnumpy().astype(_np.float32) if isinstance(src, NDArray) \
        else src.astype(_np.float32)
    a = a - mean
    if std is not None:
        a = a / std
    return nd_array(a)


class Augmenter:
    """Base augmenter (reference: image.py:570)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd_array(src.asnumpy()[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = _np.asarray(mean) if mean is not None else None
        self.std = _np.asarray(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd_array(src.asnumpy().astype(_np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        a = src.asnumpy().astype(_np.float32)
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = (a * self._coef).sum() * 3.0 / a.size
        return nd_array(a * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], dtype=_np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        a = src.asnumpy().astype(_np.float32)
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (a * self._coef).sum(axis=2, keepdims=True)
        return nd_array(a * alpha + gray * (1 - alpha))


class HueJitterAug(Augmenter):
    _to_yiq = _np.array([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]])
    _from_yiq = _np.linalg.inv(_to_yiq)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        a = src.asnumpy().astype(_np.float32)
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        rot = _np.array([[1, 0, 0], [0, u, -w], [0, w, u]])
        m = self._from_yiq @ rot @ self._to_yiq
        return nd_array(a @ m.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval)
        self.eigvec = _np.asarray(eigvec)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd_array(src.asnumpy().astype(_np.float32) + rgb)


class RandomGrayAug(Augmenter):
    _coef = _np.array([[0.299], [0.587], [0.114]], dtype=_np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            a = src.asnumpy().astype(_np.float32)
            gray = a @ self._coef
            return nd_array(_np.broadcast_to(gray, a.shape).copy())
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list (reference: image.py:1015)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Image iterator over .rec or .lst files with augmentation
    (reference: image.py:1120 — the pure-Python analogue of the C++
    ImageRecordIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 dtype="float32", last_batch_handle="pad", **kwargs):
        from .. import recordio
        from ..io.io import DataDesc, DataBatch
        assert path_imgrec or path_imglist or imglist is not None
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._DataBatch = DataBatch
        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                     "r")
            self.seq = list(self.imgrec.keys)
        else:
            if path_imglist:
                entries = []
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        entries.append((float(parts[1]),
                                        parts[-1]))
                self.imglist = entries
            else:
                self.imglist = imglist
            self.path_root = path_root or "."
            self.seq = list(range(len(self.imglist)))
        if num_parts > 1:
            self.seq = self.seq[part_index::num_parts]
        self.shuffle = shuffle
        if aug_list is None:
            aug_list = CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize",
                         "rand_mirror", "mean", "std", "brightness",
                         "contrast", "saturation", "hue", "pca_noise",
                         "rand_gray", "inter_method")})
        self.auglist = aug_list
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, label_width)
                                       if label_width > 1 else
                                       (batch_size,))]
        self.dtype = dtype
        self.cursor = 0
        self.reset()

    def reset(self):
        if self.shuffle:
            pyrandom.shuffle(self.seq)
        self.cursor = 0

    def __iter__(self):
        return self

    def next_sample(self):
        if self.cursor >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cursor]
        self.cursor += 1
        if self.imgrec is not None:
            from .. import recordio
            header, img = recordio.unpack_img(self.imgrec.read_idx(idx))
            return header.label, img
        label, fname = self.imglist[idx]
        img = imread(os.path.join(self.path_root, fname)).asnumpy()
        return label, img

    def next(self):
        batch_data = _np.zeros((self.batch_size,) + self.data_shape,
                               dtype=self.dtype)
        batch_label = _np.zeros(self.provide_label[0].shape[1:] and
                                (self.batch_size, self.label_width) or
                                (self.batch_size,), dtype=self.dtype)
        if self.label_width == 1:
            batch_label = _np.zeros((self.batch_size,), dtype=self.dtype)
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                label, img = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                break
            data = nd_array(img)
            for aug in self.auglist:
                data = aug(data)
            a = data.asnumpy()
            if a.ndim == 3 and a.shape[2] == self.data_shape[0]:
                a = a.transpose(2, 0, 1)  # HWC → CHW
            batch_data[i] = a
            if self.label_width == 1:
                batch_label[i] = label if _np.isscalar(label) else \
                    _np.asarray(label).reshape(-1)[0]
            else:
                batch_label[i] = _np.asarray(label).reshape(-1)[
                    :self.label_width]
            i += 1
        return self._DataBatch(data=[nd_array(batch_data)],
                               label=[nd_array(batch_label)], pad=pad)

    def __next__(self):
        return self.next()
