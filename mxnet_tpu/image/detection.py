"""Detection image iterator + bbox-aware augmenters.

Reference: python/mxnet/image/detection.py (DetAugmenter zoo, ImageDetIter
— labels are [header_width, obj_width, class, xmin, ymin, xmax, ymax,
...] per image). Subset: the core crop/flip/resize augmenters that adjust
boxes, and ImageDetIter over .rec/.lst.
"""
from __future__ import annotations

import random as pyrandom

import numpy as _np

from ..ndarray import NDArray, array as nd_array
from .image import (Augmenter, imresize, ImageIter, CastAug,
                    ColorNormalizeAug)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetResizeAug", "CreateDetAugmenter",
           "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter: __call__(src, label) (reference:
    detection.py:40)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only augmenter (reference: detection.py:71)."""

    def __init__(self, augmenter):
        super().__init__()
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__()
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() >= self.skip_prob and self.aug_list:
            aug = pyrandom.choice(self.aug_list)
            src, label = aug(src, label)
        return src, label


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image + boxes (reference: detection.py:114)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = nd_array(src.asnumpy()[:, ::-1].copy())
            label = label.copy()
            valid = label[:, 0] >= 0
            tmp = 1.0 - label[valid, 1]
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = tmp
        return src, label


class DetResizeAug(DetAugmenter):
    """Resize only (boxes are relative, unchanged)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1],
                        self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_mirror=False, mean=None,
                       std=None, **kwargs):
    """reference: detection.py:500."""
    auglist = [DetResizeAug((data_shape[2], data_shape[1]))]
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator (reference: detection.py:562). Labels are 2-D
    (max_objects, 5): [class, xmin, ymin, xmax, ymax] normalized."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, shuffle=False,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="label", max_objects=50, **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_mirror", "mean", "std")})
        self.max_objects = max_objects
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        self.det_auglist = aug_list
        from ..io.io import DataDesc
        self.provide_label = [DataDesc(
            label_name, (batch_size, max_objects, 5))]

    def _parse_label(self, label):
        """Flat record label → (N,5) array (reference:
        detection.py _parse_label: [hw, ow, cls,x1,y1,x2,y2,...])."""
        raw = _np.asarray(label, dtype=_np.float32).ravel()
        if raw.size < 2:
            return _np.full((self.max_objects, 5), -1, _np.float32)
        hw = int(raw[0])
        ow = int(raw[1])
        body = raw[hw:]
        n = body.size // ow
        out = _np.full((self.max_objects, 5), -1, dtype=_np.float32)
        for i in range(min(n, self.max_objects)):
            rec = body[i * ow:(i + 1) * ow]
            out[i, 0] = rec[0]
            out[i, 1:5] = rec[1:5]
        return out

    def next(self):
        batch_data = _np.zeros((self.batch_size,) + self.data_shape,
                               dtype=self.dtype)
        batch_label = _np.full(
            (self.batch_size, self.max_objects, 5), -1, dtype=self.dtype)
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                label, img = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                break
            lab = self._parse_label(label)
            data = nd_array(img)
            for aug in self.det_auglist:
                data, lab = aug(data, lab)
            a = data.asnumpy()
            if a.ndim == 3 and a.shape[2] == self.data_shape[0]:
                a = a.transpose(2, 0, 1)
            batch_data[i] = a
            batch_label[i] = lab
            i += 1
        return self._DataBatch(data=[nd_array(batch_data)],
                               label=[nd_array(batch_label)], pad=pad)
