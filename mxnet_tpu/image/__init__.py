"""Image I/O + augmentation (reference: python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .native_iter import (  # noqa: F401
    ImageRecordIterNative, native_pipeline_available)
