"""Global PRNG state.

TPU-native replacement for the reference's per-device resource RNG
(reference: include/mxnet/random_generator.h, src/resource.cc kRandom).
JAX PRNGs are counter-based and functional; the imperative API keeps a
process-global key that is split per call — same user contract as
``mx.random.seed`` (python/mxnet/random.py) with deterministic replay.

Functional code paths (hybridized blocks, pjit training steps) should NOT
use this module — they thread explicit keys (see gluon.block rng plumbing).
"""
from __future__ import annotations

import itertools
import threading

import jax


class _Counter:
    """itertools.count with a readable position — checkpointing the RNG
    requires knowing how many keys have been drawn so a restored
    process replays the exact same stream. Locked: unlike
    itertools.count's C-level __next__, a Python read-modify-write is
    not atomic under the GIL, and concurrent eager draws (the threaded
    inference paths) must never hand two threads the same position."""

    __slots__ = ("value", "_lock")

    def __init__(self, start=0):
        self.value = start
        self._lock = threading.Lock()

    def __next__(self):
        with self._lock:
            v = self.value
            self.value += 1
        return v

    def __iter__(self):
        return self


_seed = 0
_counter = _Counter()
_base_key = None


def seed(seed_state: int, ctx=None):
    """Seed the global RNG (reference: mx.random.seed). ``ctx`` is accepted
    for API parity; JAX keys are device-independent.

    Also resets the module-private numpy RandomState that host-side init
    paths (parameter initializers) draw from — so seeded runs produce
    byte-identical parameters in every process (required for multi-host
    SPMD, where 'replicated' means replicated) without touching the
    user's global numpy RNG stream."""
    global _seed, _base_key, _counter, _host_rng
    _seed = int(seed_state)
    _base_key = jax.random.key(_seed)
    _counter = _Counter()
    _host_rng = None


def get_state():
    """Snapshot the global RNG for checkpointing: (seed, #keys drawn).
    JAX keys are counter-based, so this pair fully determines every
    future draw — a restored process continues the identical stream."""
    return {"seed": _seed, "draws": _counter.value}


def set_state(state):
    """Restore a snapshot taken by :func:`get_state`."""
    seed(int(state["seed"]))
    _counter.value = int(state["draws"])


_host_rng = None


def host_rng():
    """Module-private numpy RandomState for host-side (non-traced)
    random draws, seeded by mx.random.seed. Initializers use this
    instead of numpy's global RNG (which belongs to user code)."""
    global _host_rng
    if _host_rng is None:
        import numpy as _np
        _host_rng = _np.random.RandomState(_seed & 0xFFFFFFFF)
    return _host_rng


def base_key():
    """The process PRNG root key (creating it from seed 0 if unseeded).
    Compiled whole-step programs take this as an INPUT together with a
    host-drawn counter position (:func:`reserve_draw`) and fold the two
    inside the program — the eager ``next_key()`` fold_in would cost one
    extra device dispatch per training step."""
    global _base_key
    if _base_key is None:
        seed(0)
    return _base_key


def reserve_draw():
    """Advance the global draw counter on host and return the reserved
    position. Pure host arithmetic (no device work); the checkpointed
    (seed, draws) pair covers these draws, so restored runs replay the
    identical stream."""
    return next(_counter)


def next_key():
    global _base_key
    ts = getattr(_trace_tls, "state", None)
    if ts is not None:
        key, counter = ts
        return jax.random.fold_in(key, next(counter))
    if _base_key is None:
        seed(0)
    return jax.random.fold_in(_base_key, next(_counter))


# Trace override: while a CachedOp/hybridized block is being traced into
# jit, next_key() must derive from a traced input key (a concrete key would
# bake the dropout mask into the compiled program as a constant).
# Thread-local: a trace in one thread must not reroute another thread's
# eager draws (thread-safe inference, reference cached_op_threadsafe.h:82).
_trace_tls = threading.local()


def push_trace_key(key):
    old = getattr(_trace_tls, "state", None)
    _trace_tls.state = (key, itertools.count())
    return old


def pop_trace_key(old):
    _trace_tls.state = old
