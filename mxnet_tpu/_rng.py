"""Global PRNG state.

TPU-native replacement for the reference's per-device resource RNG
(reference: include/mxnet/random_generator.h, src/resource.cc kRandom).
JAX PRNGs are counter-based and functional; the imperative API keeps a
process-global key that is split per call — same user contract as
``mx.random.seed`` (python/mxnet/random.py) with deterministic replay.

Functional code paths (hybridized blocks, pjit training steps) should NOT
use this module — they thread explicit keys (see gluon.block rng plumbing).
"""
from __future__ import annotations

import itertools
import jax

_seed = 0
_counter = itertools.count()
_base_key = None


def seed(seed_state: int, ctx=None):
    """Seed the global RNG (reference: mx.random.seed). ``ctx`` is accepted
    for API parity; JAX keys are device-independent."""
    global _seed, _base_key, _counter
    _seed = int(seed_state)
    _base_key = jax.random.key(_seed)
    _counter = itertools.count()


def next_key():
    global _base_key
    if _base_key is None:
        seed(0)
    return jax.random.fold_in(_base_key, next(_counter))
