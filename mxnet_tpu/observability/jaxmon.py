"""jax.monitoring → MetricsRegistry bridge.

XLA compilation is the dominant hidden cost on TPU (a new input shape
mid-serving or mid-training stalls the program for seconds), but jax
only surfaces it through ``jax.monitoring`` callback events. This
bridge turns those events into first-class registry metrics so compile
behaviour lands in the same exposition as step timing and serving
latency:

- ``mxtpu_xla_compile_total``        counter — backend (XLA) compiles
- ``mxtpu_xla_compile_seconds``      histogram — per-compile duration
- ``mxtpu_xla_cache_hits_total``     counter — compilation-cache hits
- ``mxtpu_xla_events_total{event=}`` counter — every other monitoring
  event, by (low-cardinality) event name

The backend-compile event fires exactly once per XLA compilation
anywhere in the process, which is what makes "zero recompiles after
warmup" assertable; :func:`mxnet_tpu.serving.telemetry.compile_count`
is a thin view over the counter registered here.

Install is idempotent and lazy — nothing imports jax until the first
caller needs the bridge.
"""
from __future__ import annotations

import threading

from .registry import get_registry

__all__ = ["install_jax_monitoring_bridge", "compile_count",
           "COMPILE_EVENT"]

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Compiles run 10ms .. minutes; the default latency edges top out too
# low to resolve them.
_COMPILE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0, 30.0, 60.0, 120.0, 300.0, 600.0)

_installed = False
_lock = threading.Lock()


def _metrics():
    reg = get_registry()
    return (
        reg.counter("mxtpu_xla_compile_total",
                    "XLA backend compilations since bridge install."),
        reg.histogram("mxtpu_xla_compile_seconds",
                      "Duration of each XLA backend compilation.",
                      buckets=_COMPILE_BUCKETS),
        reg.counter("mxtpu_xla_cache_hits_total",
                    "jax compilation-cache hits."),
        reg.counter("mxtpu_xla_events_total",
                    "Other jax.monitoring events by name.", ("event",)),
    )


def install_jax_monitoring_bridge():
    """Register the jax.monitoring listeners once per process. Safe to
    call from anywhere (serving warmup, bench, tests); only deltas
    after the first install are meaningful."""
    global _installed
    with _lock:
        if _installed:
            return get_registry()
        import jax.monitoring
        compile_total, compile_secs, cache_hits, events = _metrics()

        def _on_duration(name, duration_secs, **kw):
            if name == COMPILE_EVENT:
                compile_total.inc()
                compile_secs.observe(duration_secs)

        def _on_event(name, **kw):
            if "cache_hit" in name:
                cache_hits.inc()
            else:
                events.labels(event=name).inc()

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        jax.monitoring.register_event_listener(_on_event)
        _installed = True
        return get_registry()


def compile_count():
    """Process-global XLA compile count (installs the bridge lazily, so
    compiles before the first call are not counted)."""
    install_jax_monitoring_bridge()
    return int(get_registry()
               .counter("mxtpu_xla_compile_total").value)
