"""Opt-in histogram exemplars: (req id, trace span id) on bucket counts.

A fixed-edge histogram tells you *one* request landed in the 250–500ms
bucket; an exemplar tells you *which one* — so an SLO breach links
straight from the offending latency bucket to the request's full span
timeline in a flight bundle (``tools/flight_inspect.py`` performs the
join: exemplar → flight events by req id → trace spans by span id).

Mechanics (OpenMetrics-shaped, zero new sampling paths):

- :meth:`~.registry.HistogramChild.observe` takes an optional
  ``exemplar=(req, span_id)``; when given, the observation's bucket
  keeps it in a small last-K reservoir (``EXEMPLARS_PER_BUCKET``,
  newest wins) under the histogram's existing lock — memory stays
  O(buckets * K) forever;
- passing ``exemplar=None`` (the default everywhere) costs one ``is
  None`` test — recorder-off hot paths allocate nothing, which the
  flight tests counter-assert;
- call sites only BUILD the exemplar tuple when the flight recorder is
  enabled (``ServingStats.record_batch``,
  ``LLMStats.record_first_token`` / ``record_completed`` thread it
  through), so exemplars are strictly opt-in;
- :func:`collect` snapshots the reservoirs of a named set of
  histograms into the JSON shape ``exemplars.json`` embeds, keyed by
  metric name → label set → bucket upper edge (``le`` semantics, with
  ``+Inf`` for the overflow bucket).
"""
from __future__ import annotations

__all__ = ["EXEMPLARS_PER_BUCKET", "collect", "child_exemplars"]

# last-K reservoir per bucket: enough to name offenders without
# letting a hot bucket grow a sample log
EXEMPLARS_PER_BUCKET = 4


def child_exemplars(child):
    """One :class:`~.registry.HistogramChild`'s reservoirs as
    ``{bucket_index: [{value, req, span_id, ts_unix}, ...]}`` (oldest
    first). Empty when the child never saw an exemplar."""
    ex = child._exemplars
    if not ex:
        return {}
    with child._lock:
        items = [(i, list(lst)) for i, lst in ex.items()]
    return {i: [{"value": v, "req": r, "span_id": s, "ts_unix": ts}
                for (v, r, s, ts) in lst]
            for i, lst in items}


def _edge_name(edges, i):
    return ("%.12g" % edges[i]) if i < len(edges) else "+Inf"


def collect(registry, names):
    """Snapshot the exemplar reservoirs of ``names`` (histogram metric
    names) from ``registry``: ``{metric: [{labels, buckets: {le:
    [exemplar, ...]}}, ...]}`` — the ``exemplars.json`` bundle shape.
    Metrics absent from the registry (subsystem never instantiated)
    are skipped."""
    from .registry import Histogram
    out = {}
    for name in names:
        m = registry.get(name)
        if m is None or not isinstance(m, Histogram):
            continue
        series = []
        for child in m.children():
            by_idx = child_exemplars(child)
            if not by_idx:
                continue
            series.append({
                "labels": child.labels_dict,
                "buckets": {_edge_name(m.buckets, i): exs
                            for i, exs in sorted(by_idx.items())},
            })
        if series:
            out[name] = series
    return out
